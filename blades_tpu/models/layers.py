"""Shared layers: stateless batch normalisation, keyed dropout, and the
pack-axis dense primitive.

The reference pins ``track_running_stats=False`` on every BatchNorm
(ref: fllib/models/cifar10/resnet_cifar.py:10-18) so that federated weight
averaging never mixes desynchronised running statistics.  In JAX that
semantics is *simpler* than the stateful default: normalise by the current
batch's statistics, carry no state at all.  This keeps model application a
pure function ``(params, x) -> logits`` — which is what lets per-client
models be a stacked-params ``vmap``.

**Keyed dropout** (:func:`keyed_dropout`): dropout masks derived from an
explicit per-call key via ``fold_in(key, layer_index)`` instead of flax's
scope-path ``make_rng`` folding.  The mask then depends only on
``(key, layer index)`` — not on the module tree it is called from — which
is what lets the client lane-packing path (:mod:`blades_tpu.parallel.
packed`) reproduce each client's masks exactly inside a structurally
different grouped-kernel module.  Models opting in carry
``explicit_dropout = True`` and take ``dropout_key=`` as a call argument
(:meth:`blades_tpu.core.task.Task.apply` routes it).

**PackedDense**: P clients' ``(fin, fout)`` dense layers as one
``(P, fin, fout)`` block-batched einsum over ``(B, P, fin)`` activations —
the pack-axis formulation of ``nn.Dense`` (same contraction per group, no
cross-group terms), sized so narrow per-client matmuls still tile the MXU.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

# --------------------------------------------------------------------------
# Hand-written batch-stats-norm VJP
# --------------------------------------------------------------------------
#
# Autodiff of the naive mean/var formulation leaves XLA with five
# separate reductions per BN layer in the backward; writing the standard
# BN backward by hand (two reductions, dscale reused for the dx projection)
# measured ~4% off the whole vmapped ResNet-10 training block on a v5e
# (artifacts/perf_r4/time_bn.py).  Stats accumulate in f32 with a
# two-pass centered variance (robust for any |mean|/std the activations
# reach); the backward is where the win lives.


def _bn_normalize(x, axes, eps, keepdims=False):
    """f32 stats + normalize shared by every BatchStatsNorm branch.
    Two-pass CENTERED variance: the one-pass E[x^2] - mean^2 form loses
    the variance entirely to f32 rounding when |mean|/std > ~2^12, which
    f32 activations can hit.

    Returns ``(xhat, mean, r)`` with mean/r cast to ``x.dtype``.
    """
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=keepdims)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=keepdims)
    r = lax.rsqrt(var + eps)
    mean = mean.astype(x.dtype)
    r = r.astype(x.dtype)
    return (x - mean) * r, mean, r


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_apply(x, scale, bias, eps):
    y, _ = _bn_apply_fwd(x, scale, bias, eps)
    return y


def _bn_apply_fwd(x, scale, bias, eps):
    axes = tuple(range(x.ndim - 1))
    n = 1
    for a in axes:
        n *= x.shape[a]
    xhat, mean, r = _bn_normalize(x, axes, eps)
    y = xhat * scale + bias
    # Residuals: x is the producing conv's output, which XLA materializes
    # anyway — saving xhat instead would add one full activation set per
    # BN layer and compile-OOMs the 1000-client bench block.
    return y, (x, mean, r, scale, n)


def _bn_apply_bwd(eps, res, dy):
    x, mean, r, scale, n = res
    axes = tuple(range(dy.ndim - 1))
    xhat = (x - mean) * r
    dbias = jnp.sum(dy.astype(jnp.float32), axis=axes).astype(dy.dtype)
    dscale = jnp.sum((dy * xhat).astype(jnp.float32), axis=axes).astype(
        dy.dtype)
    dxhat = dy * scale
    mean_dxhat = jnp.sum(dxhat.astype(jnp.float32), axis=axes).astype(
        dy.dtype) / n
    mean_dxhat_xhat = dscale * scale / n
    dx = r * (dxhat - mean_dxhat - xhat * mean_dxhat_xhat)
    return dx, dscale, dbias


_bn_apply.defvjp(_bn_apply_fwd, _bn_apply_bwd)


def keyed_dropout(x, rate, key, layer_index, deterministic):
    """Inverted dropout with an explicitly derived mask key.

    ``mask = bernoulli(fold_in(key, layer_index), 1 - rate, x.shape)`` —
    a pure function of the call-site key and the layer's index, so the
    packed execution path can regenerate client ``g``'s mask from client
    ``g``'s key regardless of module structure.  ``deterministic=True``
    (eval) is the identity and needs no key.
    """
    if deterministic or rate == 0.0:
        return x
    if key is None:
        raise ValueError(
            "train-mode dropout needs an explicit dropout key: pass "
            "dropout_key= to the model call (Task.apply threads it)"
        )
    keep = 1.0 - rate
    mask = jax.random.bernoulli(
        jax.random.fold_in(key, layer_index), keep, x.shape
    )
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


def packed_keyed_dropout(x, rate, keys, layer_index, deterministic):
    """:func:`keyed_dropout` over pack-axis activations ``(B, P, F)``.

    Group ``g``'s mask is ``bernoulli(fold_in(keys[g], layer_index),
    1 - rate, (B, F))`` — exactly the mask the unpacked model draws for
    client ``g`` under ``dropout_key = keys[g]``, which is what makes the
    packed trajectory match the unpacked one beyond fp reassociation.
    """
    if deterministic or rate == 0.0:
        return x
    if keys is None:
        raise ValueError(
            "train-mode packed dropout needs per-group keys: pass "
            "dropout_keys= (P keys, one per packed client)"
        )
    keep = 1.0 - rate
    batch, _, feat = x.shape

    def one_group(k):
        return jax.random.bernoulli(
            jax.random.fold_in(k, layer_index), keep, (batch, feat)
        )

    mask = jnp.moveaxis(jax.vmap(one_group)(keys), 0, 1)  # (B, P, F)
    return jnp.where(mask, x / keep, jnp.zeros_like(x))


class PackedDense(nn.Module):
    """P clients' dense layers as one block-batched einsum.

    Params mirror ``nn.Dense`` with a leading pack axis: ``kernel``
    ``(P, fin, fout)``, ``bias`` ``(P, fout)`` — exactly
    ``jnp.stack`` of the per-client leaves, which is the pack rule
    :mod:`blades_tpu.parallel.packed` applies.  Input/output are
    ``(B, P, fin)`` / ``(B, P, fout)``; group ``g`` only ever contracts
    with slice ``kernel[g]``, so no activations cross packed clients.
    """

    features: int
    pack: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        fin = x.shape[-1]
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(),
            (self.pack, fin, self.features),
        )
        y = jnp.einsum("bpi,pio->bpo", x, kernel.astype(x.dtype))
        if self.use_bias:
            bias = self.param(
                "bias", nn.initializers.zeros, (self.pack, self.features)
            )
            y = y + bias.astype(y.dtype)[None]
        return y


class BatchStatsNorm(nn.Module):
    """Batch-statistics-only normalisation with learned scale/bias."""

    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        import os

        features = x.shape[-1]
        scale = (
            self.param("scale", nn.initializers.ones, (features,))
            if self.use_scale
            else None
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (features,))
            if self.use_bias
            else None
        )
        # Escape hatch to the pre-r4 two-pass jnp.mean/jnp.var autodiff
        # formulation.  Read at TRACE time: flipping it after a jitted
        # program compiled has no effect on that program — set it before
        # the first forward (fresh process), like BLADES_TPU_NO_PALLAS.
        hand_vjp = os.environ.get("BLADES_TPU_BN_VJP", "1") != "0"  # blades-lint: disable=jit-purity — documented fresh-process escape hatch, trace-time by contract (see comment above)
        if scale is not None and bias is not None and hand_vjp:
            return _bn_apply(x, scale.astype(x.dtype),
                             bias.astype(x.dtype), self.epsilon)
        axes = tuple(range(x.ndim - 1))
        if hand_vjp:  # use_scale/use_bias off: stats formula still
            y = _bn_normalize(x, axes, self.epsilon)[0]  # matches _bn_apply
        else:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            y = (x - mean) * lax.rsqrt(var + self.epsilon)
        if scale is not None:
            y = y * scale
        if bias is not None:
            y = y + bias
        return y
