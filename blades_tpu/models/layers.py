"""Shared layers: stateless batch normalisation + client-grouped compute.

The reference pins ``track_running_stats=False`` on every BatchNorm
(ref: fllib/models/cifar10/resnet_cifar.py:10-18) so that federated weight
averaging never mixes desynchronised running statistics.  In JAX that
semantics is *simpler* than the stateful default: normalise by the current
batch's statistics, carry no state at all.  This keeps model application a
pure function ``(params, x) -> logits`` — which is what lets per-client
models be a stacked-params ``vmap``.

Client-grouped mode (the FedSGD fast path)
------------------------------------------

``vmap``-ing a local SGD step over clients makes every conv a
batch-grouped conv and pushes XLA into split activation layouts —
profiled at ~2x the cost of the same math on one merged batch (see
:mod:`blades_tpu.core.fedsgd`).  When every client starts the step from
the SAME global params (``num_batches_per_round == 1``, the reference's
default, ref: fllib/algorithms/algorithm_config.py:63), the forward and
the data-gradient backward are client-independent and can run on one
merged ``(G*B, ...)`` batch with shared weights.  Only two things are
per-client:

- normalisation statistics — handled here by computing mean/var per
  client-group of ``B`` consecutive samples, and
- weight gradients — handled by *phantom parameters*: every layer output
  is ``f(x, stop_grad(w)) + phantom(x, pw)`` where ``pw`` is a per-client
  zero tensor and ``phantom`` is a custom-vjp function that returns zeros
  in the forward pass (the layer is linear in its weights, and ``pw == 0``)
  but whose weight cotangent is the *per-client* weight gradient.  The
  phantom forward is dead code XLA removes; the backward adds exactly one
  batch-grouped weight-grad contraction per layer — the only part of the
  step that is irreducibly per-client.

Layers enter grouped mode when called under :func:`client_grouped`; the
phantom tensors arrive through a ``"phantoms"`` flax collection whose
tree mirrors ``params`` with a leading group axis.  The classes are named
``Conv``/``Dense`` so flax module paths (and therefore param trees and
init draws) stay identical to ``nn.Conv``/``nn.Dense``.

IMPORTANT CONTRACT: phantom values must be zero.  The custom vjps return
zero input-cotangents (``d out / d x = pw = 0``); nonzero phantoms would
make the gradients silently wrong.
"""

from __future__ import annotations

import contextlib
import contextvars
from functools import partial
from typing import Any, Optional, Sequence, Tuple, Union

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

_CLIENT_GROUPS: contextvars.ContextVar[Optional[int]] = contextvars.ContextVar(
    "blades_tpu_client_groups", default=None
)


def current_groups() -> Optional[int]:
    """Number of client groups in the active grouped context, or None."""
    return _CLIENT_GROUPS.get()


@contextlib.contextmanager
def client_grouped(groups: int):
    """Trace model application in client-grouped mode: the batch axis is
    ``G`` client blocks of ``B`` consecutive samples."""
    tok = _CLIENT_GROUPS.set(int(groups))
    try:
        yield
    finally:
        _CLIENT_GROUPS.reset(tok)


# --------------------------------------------------------------------------
# Phantom custom-vjp primitives (zero forward, per-client weight cotangent)
# --------------------------------------------------------------------------


@partial(jax.custom_vjp, nondiff_argnums=(2, 3, 4, 5))
def _phantom_conv(x, pw, strides, padding, out_shape, pw_meta):
    del pw, strides, padding, pw_meta
    return jnp.zeros(out_shape, x.dtype)


def _phantom_conv_fwd(x, pw, strides, padding, out_shape, pw_meta):
    del pw
    return jnp.zeros(out_shape, x.dtype), x


def _phantom_conv_bwd(strides, padding, out_shape, pw_meta, res, dy):
    del out_shape
    x = res
    pw_shape, pw_dtype = pw_meta[0], jnp.dtype(pw_meta[1])
    g = pw_shape[0]
    b = x.shape[0] // g
    xg = x.reshape((g, b) + x.shape[1:])
    dyg = dy.reshape((g, b) + dy.shape[1:])

    def one_client_dw(xc, dyc):
        # d/dw of <conv(x, w), dy> — the exact weight-grad conv XLA builds
        # for the vmapped path, but batched over the group axis only.
        def inner(w):
            y = lax.conv_general_dilated(
                xc, w, strides, padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            return (y * dyc.astype(y.dtype)).sum()

        return jax.grad(inner)(jnp.zeros(pw_shape[1:], pw_dtype))

    dpw = jax.vmap(one_client_dw)(xg, dyg)
    return jnp.zeros_like(x), dpw


_phantom_conv.defvjp(_phantom_conv_fwd, _phantom_conv_bwd)


@partial(jax.custom_vjp, nondiff_argnums=(2,))
def _phantom_dense(x, pw, meta):
    del meta
    return jnp.zeros(x.shape[:-1] + (pw.shape[-1],), x.dtype)


def _phantom_dense_fwd(x, pw, meta):
    return jnp.zeros(x.shape[:-1] + (pw.shape[-1],), x.dtype), x


def _phantom_dense_bwd(meta, res, dy):
    x = res
    pw_shape, dtype_name = meta
    pw_dtype = jnp.dtype(dtype_name)
    g = pw_shape[0]
    # Fold any extra middle dims (e.g. sequence axes) into the per-client
    # contraction axis, keeping features last — matches nn.Dense, whose
    # kernel contracts only the trailing axis.
    xg = x.reshape(g, -1, x.shape[-1])
    dyg = dy.reshape(g, -1, dy.shape[-1])
    dpw = jnp.einsum("gbi,gbo->gio", xg, dyg.astype(xg.dtype),
                     preferred_element_type=jnp.float32).astype(pw_dtype)
    return jnp.zeros_like(x), dpw


_phantom_dense.defvjp(_phantom_dense_fwd, _phantom_dense_bwd)


def _sg(x):
    return lax.stop_gradient(x)


# --------------------------------------------------------------------------
# Hand-written batch-stats-norm VJP (ungrouped path)
# --------------------------------------------------------------------------
#
# Autodiff of the naive mean/var formulation leaves XLA with five
# separate reductions per BN layer in the backward; writing the standard
# BN backward by hand (two reductions, dscale reused for the dx projection)
# measured ~4% off the whole vmapped ResNet-10 training block on a v5e
# (artifacts/perf_r4/time_bn.py).  Stats accumulate in f32 with a
# two-pass centered variance (robust for any |mean|/std the activations
# reach); the backward is where the win lives.


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_apply(x, scale, bias, eps):
    y, _ = _bn_apply_fwd(x, scale, bias, eps)
    return y


def _bn_apply_fwd(x, scale, bias, eps):
    axes = tuple(range(x.ndim - 1))
    n = 1
    for a in axes:
        n *= x.shape[a]
    xhat, mean, r = _bn_normalize(x, axes, eps)
    y = xhat * scale + bias
    # Residuals: x is the producing conv's output, which XLA materializes
    # anyway — saving xhat instead would add one full activation set per
    # BN layer and compile-OOMs the 1000-client bench block.
    return y, (x, mean, r, scale, n)


def _bn_apply_bwd(eps, res, dy):
    x, mean, r, scale, n = res
    axes = tuple(range(dy.ndim - 1))
    xhat = (x - mean) * r
    dbias = jnp.sum(dy.astype(jnp.float32), axis=axes).astype(dy.dtype)
    dscale = jnp.sum((dy * xhat).astype(jnp.float32), axis=axes).astype(
        dy.dtype)
    dxhat = dy * scale
    mean_dxhat = jnp.sum(dxhat.astype(jnp.float32), axis=axes).astype(
        dy.dtype) / n
    mean_dxhat_xhat = dscale * scale / n
    dx = r * (dxhat - mean_dxhat - xhat * mean_dxhat_xhat)
    return dx, dscale, dbias


_bn_apply.defvjp(_bn_apply_fwd, _bn_apply_bwd)


def _bn_normalize(x, axes, eps, keepdims=False):
    """f32 stats + normalize shared by every branch that must numerically
    match :func:`_bn_apply` (the grouped path uses it under plain
    autodiff).  Two-pass CENTERED variance: the one-pass E[x^2] - mean^2
    form loses the variance entirely to f32 rounding when
    |mean|/std > ~2^12, which f32 activations can hit.

    Returns ``(xhat, mean, r)`` with mean/r cast to ``x.dtype``.
    """
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=keepdims)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=keepdims)
    r = lax.rsqrt(var + eps)
    mean = mean.astype(x.dtype)
    r = r.astype(x.dtype)
    return (x - mean) * r, mean, r


def _grouped_affine(vec, phantom, groups, ndim):
    """Per-client channel vector ``stop_grad(vec) + phantom`` broadcast to a
    ``(G, ...)``-grouped activation of rank ``ndim`` (including the group
    axis).  Pure autodiff — gradients w.r.t. ``phantom`` are per-client
    channel reductions XLA fuses natively (no custom vjp, so no forced
    residual materialisation)."""
    eff = _sg(vec)[None, :].astype(phantom.dtype) + phantom
    return eff.reshape((groups,) + (1,) * (ndim - 2) + (vec.shape[-1],))


# --------------------------------------------------------------------------
# Group-aware drop-in layers (flax paths match nn.Conv / nn.Dense)
# --------------------------------------------------------------------------


def _norm_padding(padding, kernel_size):
    if isinstance(padding, str):
        return padding
    if isinstance(padding, int):
        return tuple((padding, padding) for _ in kernel_size)
    out = []
    for p in padding:
        out.append((p, p) if isinstance(p, int) else tuple(p))
    return tuple(out)


class Conv(nn.Module):
    """Drop-in for ``nn.Conv`` (NHWC/HWIO) with client-grouped support.

    Same param names/shapes/initialisers as ``nn.Conv`` so module paths,
    init draws and checkpoints are interchangeable.
    """

    features: int
    kernel_size: Sequence[int]
    strides: Union[int, Sequence[int]] = 1
    padding: Any = "SAME"
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        ks = tuple(self.kernel_size)
        strides = (
            (self.strides,) * len(ks)
            if isinstance(self.strides, int)
            else tuple(self.strides)
        )
        padding = _norm_padding(self.padding, ks)
        kernel = self.param(
            "kernel",
            nn.initializers.lecun_normal(),
            ks + (x.shape[-1], self.features),
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (self.features,))
            if self.use_bias
            else None
        )
        dt = jnp.promote_types(x.dtype, kernel.dtype)
        x = x.astype(dt)
        kernel = kernel.astype(dt)
        groups = current_groups()
        if groups is None:
            y = lax.conv_general_dilated(
                x, kernel, strides, padding,
                dimension_numbers=("NHWC", "HWIO", "NHWC"),
            )
            if bias is not None:
                y = y + bias.astype(dt)
            return y
        # Grouped: shared-weight conv (stop-grad) + per-client phantoms.
        pw = _get_phantom(self, "kernel", dt)
        y = lax.conv_general_dilated(
            x, _sg(kernel), strides, padding,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
        )
        y = y + _phantom_conv(_sg(x), pw, strides, padding, tuple(y.shape),
                              (tuple(pw.shape), pw.dtype.name))
        if bias is not None:
            pb = _get_phantom(self, "bias", dt)
            b = y.shape[0] // groups
            yr = y.reshape((groups, b) + y.shape[1:])
            yr = yr + _grouped_affine(bias.astype(dt), pb, groups, yr.ndim)
            y = yr.reshape(y.shape)
        return y


class Dense(nn.Module):
    """Drop-in for ``nn.Dense`` with client-grouped support."""

    features: int
    use_bias: bool = True

    @nn.compact
    def __call__(self, x):
        kernel = self.param(
            "kernel", nn.initializers.lecun_normal(), (x.shape[-1], self.features)
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (self.features,))
            if self.use_bias
            else None
        )
        dt = jnp.promote_types(x.dtype, kernel.dtype)
        x = x.astype(dt)
        groups = current_groups()
        if groups is None:
            y = x @ kernel.astype(dt)
            if bias is not None:
                y = y + bias.astype(dt)
            return y
        pw = _get_phantom(self, "kernel", dt)
        y = x @ _sg(kernel.astype(dt))
        y = y + _phantom_dense(_sg(x), pw, (tuple(pw.shape), pw.dtype.name))
        if bias is not None:
            pb = _get_phantom(self, "bias", dt)
            b = y.shape[0] // groups
            yr = y.reshape((groups, b) + y.shape[1:])
            yr = yr + _grouped_affine(bias.astype(dt), pb, groups, yr.ndim)
            y = yr.reshape(y.shape)
        return y


def _get_phantom(mod: nn.Module, name: str, dt) -> jax.Array:
    """Fetch this layer's phantom tensor from the ``phantoms`` collection
    (provided by :mod:`blades_tpu.core.fedsgd`; mirrors the param tree
    with a leading group axis)."""
    if not mod.has_variable("phantoms", name):
        raise ValueError(
            "client-grouped mode needs a 'phantoms' collection mirroring "
            f"params (missing {name!r} under {mod.name!r}); build it with "
            "blades_tpu.core.fedsgd.make_phantoms"
        )
    v = mod.get_variable("phantoms", name)
    return v.astype(dt)


class BatchStatsNorm(nn.Module):
    """Batch-statistics-only normalisation with learned scale/bias.

    In client-grouped mode the statistics are per client group (each
    group's ``B`` consecutive samples), matching what ``vmap`` over
    clients computes, and scale/bias gradients flow through phantoms.
    """

    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        features = x.shape[-1]
        scale = (
            self.param("scale", nn.initializers.ones, (features,))
            if self.use_scale
            else None
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (features,))
            if self.use_bias
            else None
        )
        import os

        # Escape hatch to the pre-r4 two-pass jnp.mean/jnp.var stats.
        # Read at TRACE time: flipping it after a jitted program compiled
        # has no effect on that program — set it before the first forward
        # (fresh process), like BLADES_TPU_NO_PALLAS.  Governs BOTH the
        # ungrouped and the grouped branch, so the FedSGD equivalence
        # (grouped vs vmapped stats bit-matching) holds in either mode.
        hand_vjp = os.environ.get("BLADES_TPU_BN_VJP", "1") != "0"
        groups = current_groups()
        if groups is None:
            if scale is not None and bias is not None and hand_vjp:
                return _bn_apply(x, scale.astype(x.dtype),
                                 bias.astype(x.dtype), self.epsilon)
            axes = tuple(range(x.ndim - 1))
            if hand_vjp:  # use_scale/use_bias off: stats formula still
                y = _bn_normalize(x, axes, self.epsilon)[0]  # matches _bn_apply
            else:
                mean = jnp.mean(x, axis=axes)
                var = jnp.var(x, axis=axes)
                y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
            if scale is not None:
                y = y * scale
            if bias is not None:
                y = y + bias
            return y
        g = groups
        b = x.shape[0] // g
        xr = x.reshape((g, b) + x.shape[1:])
        axes = tuple(range(1, xr.ndim - 1))
        if hand_vjp:
            # Same f32 stats formula as _bn_apply_fwd — the FedSGD
            # equivalence tests compare this path against the vmapped one
            # at tight tolerance, so the stat numerics must match exactly.
            yr = _bn_normalize(xr, axes, self.epsilon, keepdims=True)[0]
        else:
            mean = jnp.mean(xr, axis=axes, keepdims=True)
            var = jnp.var(xr, axis=axes, keepdims=True)
            yr = (xr - mean) * jax.lax.rsqrt(var + self.epsilon)
        # Per-client affine via broadcast phantom params — plain autodiff,
        # so dscale_c / dbias_c are ordinary fused channel reductions.
        if scale is not None:
            ps = _get_phantom(self, "scale", yr.dtype)
            yr = yr * _grouped_affine(scale, ps, g, yr.ndim)
        if bias is not None:
            pb = _get_phantom(self, "bias", yr.dtype)
            yr = yr + _grouped_affine(bias, pb, g, yr.ndim)
        return yr.reshape(x.shape)
