"""Shared layers: stateless batch normalisation.

The reference pins ``track_running_stats=False`` on every BatchNorm
(ref: fllib/models/cifar10/resnet_cifar.py:10-18) so that federated weight
averaging never mixes desynchronised running statistics.  In JAX that
semantics is *simpler* than the stateful default: normalise by the current
batch's statistics, carry no state at all.  This keeps model application a
pure function ``(params, x) -> logits`` — which is what lets per-client
models be a stacked-params ``vmap``.
"""

from __future__ import annotations

from functools import partial

import flax.linen as nn
import jax
import jax.numpy as jnp
from jax import lax

# --------------------------------------------------------------------------
# Hand-written batch-stats-norm VJP
# --------------------------------------------------------------------------
#
# Autodiff of the naive mean/var formulation leaves XLA with five
# separate reductions per BN layer in the backward; writing the standard
# BN backward by hand (two reductions, dscale reused for the dx projection)
# measured ~4% off the whole vmapped ResNet-10 training block on a v5e
# (artifacts/perf_r4/time_bn.py).  Stats accumulate in f32 with a
# two-pass centered variance (robust for any |mean|/std the activations
# reach); the backward is where the win lives.


def _bn_normalize(x, axes, eps, keepdims=False):
    """f32 stats + normalize shared by every BatchStatsNorm branch.
    Two-pass CENTERED variance: the one-pass E[x^2] - mean^2 form loses
    the variance entirely to f32 rounding when |mean|/std > ~2^12, which
    f32 activations can hit.

    Returns ``(xhat, mean, r)`` with mean/r cast to ``x.dtype``.
    """
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=keepdims)
    var = jnp.mean(jnp.square(xf - mean), axis=axes, keepdims=keepdims)
    r = lax.rsqrt(var + eps)
    mean = mean.astype(x.dtype)
    r = r.astype(x.dtype)
    return (x - mean) * r, mean, r


@partial(jax.custom_vjp, nondiff_argnums=(3,))
def _bn_apply(x, scale, bias, eps):
    y, _ = _bn_apply_fwd(x, scale, bias, eps)
    return y


def _bn_apply_fwd(x, scale, bias, eps):
    axes = tuple(range(x.ndim - 1))
    n = 1
    for a in axes:
        n *= x.shape[a]
    xhat, mean, r = _bn_normalize(x, axes, eps)
    y = xhat * scale + bias
    # Residuals: x is the producing conv's output, which XLA materializes
    # anyway — saving xhat instead would add one full activation set per
    # BN layer and compile-OOMs the 1000-client bench block.
    return y, (x, mean, r, scale, n)


def _bn_apply_bwd(eps, res, dy):
    x, mean, r, scale, n = res
    axes = tuple(range(dy.ndim - 1))
    xhat = (x - mean) * r
    dbias = jnp.sum(dy.astype(jnp.float32), axis=axes).astype(dy.dtype)
    dscale = jnp.sum((dy * xhat).astype(jnp.float32), axis=axes).astype(
        dy.dtype)
    dxhat = dy * scale
    mean_dxhat = jnp.sum(dxhat.astype(jnp.float32), axis=axes).astype(
        dy.dtype) / n
    mean_dxhat_xhat = dscale * scale / n
    dx = r * (dxhat - mean_dxhat - xhat * mean_dxhat_xhat)
    return dx, dscale, dbias


_bn_apply.defvjp(_bn_apply_fwd, _bn_apply_bwd)


class BatchStatsNorm(nn.Module):
    """Batch-statistics-only normalisation with learned scale/bias."""

    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        import os

        features = x.shape[-1]
        scale = (
            self.param("scale", nn.initializers.ones, (features,))
            if self.use_scale
            else None
        )
        bias = (
            self.param("bias", nn.initializers.zeros, (features,))
            if self.use_bias
            else None
        )
        # Escape hatch to the pre-r4 two-pass jnp.mean/jnp.var autodiff
        # formulation.  Read at TRACE time: flipping it after a jitted
        # program compiled has no effect on that program — set it before
        # the first forward (fresh process), like BLADES_TPU_NO_PALLAS.
        hand_vjp = os.environ.get("BLADES_TPU_BN_VJP", "1") != "0"
        if scale is not None and bias is not None and hand_vjp:
            return _bn_apply(x, scale.astype(x.dtype),
                             bias.astype(x.dtype), self.epsilon)
        axes = tuple(range(x.ndim - 1))
        if hand_vjp:  # use_scale/use_bias off: stats formula still
            y = _bn_normalize(x, axes, self.epsilon)[0]  # matches _bn_apply
        else:
            mean = jnp.mean(x, axis=axes)
            var = jnp.var(x, axis=axes)
            y = (x - mean) * lax.rsqrt(var + self.epsilon)
        if scale is not None:
            y = y * scale
        if bias is not None:
            y = y + bias
        return y
