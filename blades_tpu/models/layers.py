"""Shared layers: stateless batch normalisation.

The reference pins ``track_running_stats=False`` on every BatchNorm
(ref: fllib/models/cifar10/resnet_cifar.py:10-18) so that federated weight
averaging never mixes desynchronised running statistics.  In JAX that
semantics is *simpler* than the stateful default: normalise by the current
batch's statistics, carry no state at all.  This keeps model application a
pure function ``(params, x) -> logits`` — no mutable collections, which is
what lets per-client models be a stacked-params ``vmap``.
"""

from __future__ import annotations

import flax.linen as nn
import jax
import jax.numpy as jnp


class BatchStatsNorm(nn.Module):
    """Batch-statistics-only normalisation with learned scale/bias."""

    epsilon: float = 1e-5
    use_scale: bool = True
    use_bias: bool = True

    @nn.compact
    def __call__(self, x: jnp.ndarray) -> jnp.ndarray:
        axes = tuple(range(x.ndim - 1))
        mean = jnp.mean(x, axis=axes)
        var = jnp.var(x, axis=axes)
        y = (x - mean) * jax.lax.rsqrt(var + self.epsilon)
        features = x.shape[-1]
        if self.use_scale:
            y = y * self.param("scale", nn.initializers.ones, (features,))
        if self.use_bias:
            y = y + self.param("bias", nn.initializers.zeros, (features,))
        return y
