"""Model zoo (ref: fllib/models/): MLP, FashionCNN, CIFAR ResNets, CCT.

All models are flax.linen modules that are *pure functions of params* — the
CIFAR ResNets use batch-statistics-only normalisation, matching the
reference's ``track_running_stats=False`` BatchNorm
(ref: fllib/models/cifar10/resnet_cifar.py:14,18), which is the property
that makes FL weight averaging sound (no running stats to desynchronise)
and makes ``vmap`` over per-client params trivial (no mutable collections).

Input convention is NHWC (TPU-native layout), unlike the reference's NCHW.
"""

from blades_tpu.models.catalog import ModelCatalog, register_model  # noqa: F401
from blades_tpu.models.layers import PackedDense, keyed_dropout  # noqa: F401
from blades_tpu.models.mlp import MLP, PackedMLP  # noqa: F401
from blades_tpu.models.cnn import FashionCNN, PackedFashionCNN  # noqa: F401
from blades_tpu.models.resnet import (  # noqa: F401
    PackedResNet,
    ResNet10,
    ResNet18,
    ResNet34,
    ResNet50,
    ResNet101,
    ResNet152,
)
from blades_tpu.models.cct import CCT, cct_2_3x2_32, cct_4_3x2_32, cct_7_3x1_32  # noqa: F401
