"""blades_tpu — a TPU-native Byzantine-robust federated-learning framework.

A from-scratch JAX/XLA re-design of the capabilities of the reference
``blades``/``fllib`` stack (dddkyi/blades): instead of Ray actors hosting
per-client PyTorch optimizers and shipping pseudo-gradients through an object
store, clients are a leading array axis.  Local SGD rounds are jit-compiled
trainsteps ``vmap``-ed over clients-per-chip and sharded over the ICI mesh
with ``shard_map``; robust aggregators and model-poisoning attacks are pure
``jnp`` ops on stacked ``(num_clients, num_params)`` update matrices; the
client→server gradient push is an on-device collective.

Package layout (mirrors the reference's layer map, SURVEY.md §1):

- :mod:`blades_tpu.ops`          robust aggregators (ref: fllib/aggregators/)
- :mod:`blades_tpu.adversaries`  attacks (ref: blades/adversaries/)
- :mod:`blades_tpu.models`       model zoo (ref: fllib/models/)
- :mod:`blades_tpu.data`         dataset + partitioner (ref: fllib/datasets/)
- :mod:`blades_tpu.core`         client/task/server train-step layer
                                 (ref: fllib/clients, fllib/tasks,
                                 fllib/algorithms/server.py)
- :mod:`blades_tpu.parallel`     mesh/sharding — replaces the reference's
                                 Ray execution layer (fllib/core/execution/)
                                 and NCCL communicator (fllib/communication/)
- :mod:`blades_tpu.algorithms`   FedAvg / FedAvg-DP drivers + config system
                                 (ref: fllib/algorithms, blades/algorithms)
- :mod:`blades_tpu.tune`         YAML experiment sweeps (ref: blades/train.py)
- :mod:`blades_tpu.utils`        tree/metric/checkpoint/timing utilities
"""

__version__ = "0.1.0"

from blades_tpu import ops as ops  # noqa: F401


def __getattr__(name):
    # Lazy subpackage access (keeps `import blades_tpu` light; models/data
    # pull in flax/numpy loaders only when used).
    import importlib

    if name in ("adversaries", "algorithms", "core", "data", "models",
                "parallel", "tune", "utils"):
        return importlib.import_module(f"blades_tpu.{name}")
    raise AttributeError(f"module 'blades_tpu' has no attribute {name!r}")
