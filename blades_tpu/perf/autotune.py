"""Execution autotuner: measured plan selection for the round pipeline.

The repo accumulated a deep stack of perf levers — ``execution="auto"``,
``client_packing="auto"``, ``scan_window="auto"``, the streamed
``d_chunk``, the pallas MXU-finish variants — each resolved by its own
hand-written heuristic that has never been validated against a
measurement.  This module replaces that scatter with one measured
decision, the way XLA-era systems pick tilings: enumerate the legal
space, time the candidates, cache the winner.

Three pieces:

- **Plan space** (:class:`Plan`, :func:`enumerate_plans`): legal
  candidates derived from the constraints already encoded at validate
  time, partitioned into a **numerics-preserving default tier** (knobs
  the existing equivalence tests prove bit-exact: streamed chunk sizes
  on chunk-invariant rounds, the bit-exact MXU radix counts, chained
  scan windows, prefetch) and an opt-in **reassociating tier**
  (dense<->streamed<->packed switches and the ``stats_mxu`` finish,
  which carry the documented float-reassociation tolerances).  A run
  that never opts in can only be handed a plan that reproduces the
  untuned trajectory bit for bit.
- **Trial harness** (:func:`timed_measure_fn`, :func:`select_plan`):
  each candidate compiles through the PR 3 AOT executable cache (the
  candidate's resolved knobs ARE its compile-cache fingerprint), runs
  ``warmup`` dispatches and reports the median of ``reps`` timed ones
  on the donated-buffer pipeline.  When timing is unavailable — the
  CPU tier-1 environment, or no measure function injected — selection
  falls back to the **deterministic ranked heuristic**: candidates are
  enumerated in the current resolution order, so rank 0 is exactly the
  plan today's hand-written heuristics produce and off-TPU selection is
  reproducible.  Tests inject a fake clock through ``clock=`` to drive
  the timed path deterministically.
- **Plan cache** (:class:`PlanCache`): winners persist to disk keyed
  ``(config fingerprint, autotune tier, device kind, jaxlib version)``
  using the :mod:`blades_tpu.faults.host` atomic write pattern (tmp +
  fsync + ``os.replace``).  Entries are version-stamped and
  corrupt-tolerant: a torn/garbage/stale file means re-tune, never a
  crash.  ``tools/show_plan.py`` dumps and invalidates entries.

The driver integration lives in
:meth:`blades_tpu.algorithms.fedavg.Fedavg._resolve_autotune_plan`; the
resolved plan plus per-candidate timings and the cache hit/miss flag
flow into sweep summaries (``summary["autotune"]``) and the
schema-registered round fields (``plan_id`` /
``autotune_cache_hit`` / ``autotune_timed`` / ``autotune_candidates``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import statistics
import time
import warnings
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

PLAN_CACHE_VERSION = 1
ENV_CACHE_DIR = "BLADES_TPU_PLAN_CACHE_DIR"
_DEFAULT_CACHE_DIR = "~/.cache/blades_tpu/plans"

# Streamed d_chunk candidates around the historical hard-coded default
# (1 << 17).  Small on purpose: the chunk knob trades scan-carry size
# against dispatch count, and the knee sits within one octave of the
# default on every geometry measured so far.
D_CHUNK_LADDER = (1 << 16, 1 << 17, 1 << 18)

# Enumeration ceiling.  The knob grid is small by construction, but a
# pathological composition (reassociating tier x windows x ladder) must
# not turn one trial's tuning into a compile marathon; the drop count is
# recorded in the provenance so the cap is never silent.
MAX_CANDIDATES = 32

DEFAULT_TIER = "default"
REASSOCIATING_TIER = "reassociating"


@dataclasses.dataclass(frozen=True)
class Plan:
    """One resolved execution configuration for the round pipeline.

    Every field materialises a knob the ``"auto"`` heuristics used to
    resolve independently; :func:`apply_plan` writes them back into a
    :class:`~blades_tpu.algorithms.config.FedavgConfig` before the
    driver builds its dispatch pipeline.
    """

    execution: str = "dense"          # resolved path — never "auto"
    d_chunk: int = 1 << 17            # streamed finish chunk width
    client_packing: int = 1           # clients per grouped-kernel lane
    mxu_finish: str = ""              # "" | "counts" | "all" (streamed)
    rounds_per_dispatch: int = 1      # chained scan window; 1 = per-round
    prefetch: bool = False            # dense single-round batch staging
    agg_domain: str = "f32"           # "f32" | "wire" (dense + quant codec)
    # Participation-window store (blades_tpu/state): where off-cohort
    # per-client rows live and the pinned cohort size (None = no
    # window — the pre-store program).  Backends are bit-identical by
    # contract; the knob still rides the reassociating tier because it
    # reshapes the staging pipeline rather than the numerics tiering
    # the default tier was defined over.
    state_store: str = "resident"     # "resident" | "host" | "disk"
    state_window: Optional[int] = None
    # Pod-scale knobs (ISSUE 18).  ``mesh_shape=None`` is the single-
    # chip / config-resolved-mesh baseline — the plan does not touch the
    # device layout at all, so every pre-mesh plan_id stays byte-
    # identical.  A ``(c, dd)`` pair pins the 2-D ``(clients, d)`` mesh;
    # ``collective="hier"`` switches the round to the hierarchical
    # pre-aggregating path (parallel/hier.py) — reassociating tier by
    # construction, since bucketing reassociates the defense.
    mesh_shape: Optional[Tuple[int, int]] = None
    collective: str = "ring"          # "ring" | "hier"
    tier: str = DEFAULT_TIER          # numerics tier this plan belongs to

    def __post_init__(self):
        if self.execution not in ("dense", "streamed"):
            raise ValueError(f"plan execution must be dense|streamed, "
                             f"got {self.execution!r}")
        if self.mesh_shape is not None:
            ms = tuple(int(v) for v in self.mesh_shape)
            if len(ms) != 2 or min(ms) < 1:
                raise ValueError(f"plan mesh_shape must be a (clients, d) "
                                 f"pair of positive ints, got "
                                 f"{self.mesh_shape!r}")
            # Normalise (JSON round-trips lists; the frozen dataclass
            # must still hash/compare by value for dedupe).
            object.__setattr__(self, "mesh_shape", ms)
        if self.collective not in ("ring", "hier"):
            raise ValueError(f"plan collective must be ring|hier, "
                             f"got {self.collective!r}")
        if self.collective == "hier" and self.mesh_shape is None:
            raise ValueError("plan collective='hier' needs a mesh_shape "
                             "— the hierarchical path is defined by its "
                             "(clients, d) mesh")
        if self.state_store not in ("resident", "host", "disk"):
            raise ValueError(f"plan state_store must be resident|host|"
                             f"disk, got {self.state_store!r}")
        if self.state_window is not None and int(self.state_window) < 0:
            raise ValueError(f"plan state_window must be None or >= 0, "
                             f"got {self.state_window}")
        if self.agg_domain not in ("f32", "wire"):
            raise ValueError(f"plan agg_domain must be f32|wire, "
                             f"got {self.agg_domain!r}")
        if self.mxu_finish not in ("", "counts", "all"):
            raise ValueError(f"plan mxu_finish must be ''|'counts'|'all', "
                             f"got {self.mxu_finish!r}")
        if self.tier not in (DEFAULT_TIER, REASSOCIATING_TIER):
            raise ValueError(f"unknown plan tier {self.tier!r}")
        if int(self.d_chunk) < 1024:
            raise ValueError(f"plan d_chunk must be >= 1024, "
                             f"got {self.d_chunk}")
        if int(self.client_packing) < 1:
            raise ValueError(f"plan client_packing must be >= 1, "
                             f"got {self.client_packing}")
        if int(self.rounds_per_dispatch) < 1:
            raise ValueError(f"plan rounds_per_dispatch must be >= 1, "
                             f"got {self.rounds_per_dispatch}")

    @property
    def plan_id(self) -> str:
        """Compact stable identifier, stamped per round (``plan_id``).
        The wire-domain marker is appended only when engaged, so every
        f32-domain id is byte-identical to the pre-knob format."""
        return (f"{self.execution}|c{int(self.d_chunk)}"
                f"|p{int(self.client_packing)}"
                f"|mxu={self.mxu_finish or 'off'}"
                f"|w{int(self.rounds_per_dispatch)}"
                f"|{'pre' if self.prefetch else 'nopre'}"
                + ("|wire" if self.agg_domain == "wire" else "")
                # Window-store marker only when engaged: every
                # store-free id stays byte-identical to the pre-knob
                # format (the agg_domain discipline).
                + (f"|ss={self.state_store}w{int(self.state_window)}"
                   if self.state_window is not None else "")
                # Mesh markers follow the same only-when-engaged
                # discipline: mesh-free plan ids are byte-identical to
                # the pre-pod format (regression-pinned).
                + (f"|mesh={self.mesh_shape[0]}x{self.mesh_shape[1]}"
                   if self.mesh_shape is not None else "")
                + ("|hier" if self.collective == "hier" else ""))

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Plan":
        """Parse a plan dict (checkpoint payloads, cache entries, the
        ``tuned_plan`` config pin).  Unknown keys raise — a cache entry
        written by a FUTURE plan layout must read as stale, not be
        half-applied."""
        if not isinstance(d, dict):
            raise ValueError(f"plan must be a dict, got {type(d).__name__}")
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = sorted(set(d) - known)
        if unknown:
            raise ValueError(f"unknown plan fields {unknown}")
        return cls(**d)


def apply_plan(config, plan: Plan) -> None:
    """Materialise ``plan`` into the config's knob fields (the driver
    then builds its pipeline from those exactly as an untuned run
    would).  Composition contract: a knob the user set EXPLICITLY was
    never varied by the plan space, so writing the plan back either
    repeats the user's value or resolves an ``"auto"``.
    """
    config.execution = plan.execution
    config.d_chunk = int(plan.d_chunk)
    if plan.mesh_shape is not None:
        # Pod-scale plan: pin the 2-D device layout, and the hierarchical
        # collective switches the execution path outright (the "hier"
        # round is a distinct program, not a dense variant).
        config.mesh_shape = tuple(int(v) for v in plan.mesh_shape)
        if plan.collective == "hier":
            config.execution = "hier"
    if plan.state_window is not None:
        # Window pinned by construction (the plan space never varies
        # it); the backend may have been probed, so materialise it.
        config.state_store = plan.state_store
        config.state_window = int(plan.state_window)
    if plan.execution == "dense":
        config.client_packing = (int(plan.client_packing)
                                 if plan.client_packing >= 2 else "off")
        if plan.rounds_per_dispatch == 1:
            config.prefetch = bool(plan.prefetch)
        # Wire-domain aggregation (dense + deferrable codec only; the
        # plan space never offers "wire" elsewhere, and an explicit
        # user agg_domain pins its list to one entry).
        config.agg_domain = plan.agg_domain
    else:
        config.client_packing = "off"
        config.mxu_finish = plan.mxu_finish
    rpd = int(plan.rounds_per_dispatch)
    prior = int(getattr(config, "rounds_per_dispatch", 1) or 1)
    config.rounds_per_dispatch = rpd
    if rpd > 1 and prior != rpd:
        # The chained key discipline is what makes windowed rows
        # bit-identical to round-per-dispatch execution (PR 3); every
        # window the plan space INTRODUCES comes from the sweep's
        # eligibility gate, which only ever engages chained windows.  A
        # window the USER pinned (prior == rpd — the plan space never
        # varies it) keeps the user's own chained_dispatch setting: the
        # plain multi_step discipline is a legal explicit choice the
        # tuner must not silently rewrite.
        config.chained_dispatch = True


# ---------------------------------------------------------------------------
# plan-space enumeration
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class PlanSpace:
    """Ordered candidate plans.  ``candidates[0]`` is ALWAYS the plan
    the current hand-written heuristics resolve (the heuristic-fallback
    winner); the rest follow in deterministic enumeration order.
    ``truncated`` counts candidates dropped by :data:`MAX_CANDIDATES`.
    """

    candidates: Tuple[Plan, ...]
    truncated: int = 0

    @property
    def baseline(self) -> Plan:
        return self.candidates[0]


def enumerate_plans(
    *,
    executions: Sequence[str],
    d_chunks: Sequence[int],
    mxu_modes: Sequence[str] = ("",),
    pack_factors: Sequence[int] = (1,),
    scan_windows: Sequence[int] = (1,),
    prefetch_options: Sequence[bool] = (False,),
    agg_domains: Sequence[str] = ("f32",),
    state_stores: Sequence[str] = ("resident",),
    state_windows: Sequence[Optional[int]] = (None,),
    mesh_shapes: Sequence[Optional[Tuple[int, int]]] = (None,),
    collectives: Sequence[str] = ("ring",),
    num_devices: int = 1,
    allow_reassociating: bool = False,
    max_candidates: int = MAX_CANDIDATES,
) -> PlanSpace:
    """Enumerate legal plans from per-knob candidate lists.

    Every list is ordered **baseline value first** — the caller derives
    the lists from the config's constraints (explicit settings collapse
    a list to one entry) — so the nested enumeration yields the current
    heuristic resolution as ``candidates[0]`` by construction.

    Tier assignment: switching the execution path, packing clients,
    aggregating in the quantized wire domain, or enabling the
    ``stats_mxu`` finish ("all") reassociates float reductions and
    lands in :data:`REASSOCIATING_TIER`; chunk sizes, the bit-exact
    radix counts ("counts"), chained scan windows and prefetch stay
    :data:`DEFAULT_TIER`.  Without ``allow_reassociating`` the
    reassociating tier is not enumerated at all — an un-opted run can
    never be handed one.  ``agg_domains`` applies to the dense path
    only (codecs are dense-path features; the caller gates "wire" on a
    deferrable quant codec and the absence of f32-domain-only stages).
    """
    if not executions:
        raise ValueError("executions must name at least the baseline path")
    if not d_chunks:
        raise ValueError("d_chunks must hold at least the baseline chunk")
    for ms in mesh_shapes:
        if ms is None:
            continue
        if int(num_devices) <= 1:
            raise ValueError(
                f"mesh_shape {tuple(ms)} candidates need num_devices > 1 "
                f"(got {num_devices}) — the pod-scale tier is only legal "
                "on a multi-chip run")
        if int(ms[0]) * int(ms[1]) != int(num_devices):
            raise ValueError(
                f"mesh_shape {tuple(ms)} must tile exactly "
                f"{num_devices} devices")
    plans: List[Plan] = []
    # Mesh knobs enumerate OUTERMOST, baseline (no-mesh, ring) first:
    # with the default (None,)/("ring",) lists the loop collapses to one
    # iteration and the enumeration order — hence candidates[0] and
    # every plan_id — is byte-identical to the pre-pod tuner.
    for ms in mesh_shapes:
        for coll in collectives:
            if coll == "hier" and ms is None:
                continue  # the hierarchical path is defined by its mesh
            mesh_tier = (DEFAULT_TIER
                         if ms == mesh_shapes[0] and coll == collectives[0]
                         else REASSOCIATING_TIER)
            for exe in executions:
                exe_tier = (mesh_tier if exe == executions[0]
                            else REASSOCIATING_TIER)
                if exe == "streamed" and ms is not None:
                    continue  # streamed × mesh does not exist
                for w in scan_windows:
                    if coll == "hier" and int(w) != 1:
                        continue  # hier is dispatched per-round (no scan)
                    if exe == "streamed":
                        for dc in d_chunks:
                            for mxu in mxu_modes:
                                tier = exe_tier
                                if mxu == "all" and mxu_modes[0] != "all":
                                    tier = REASSOCIATING_TIER
                                plans.append(Plan(
                                    execution="streamed", d_chunk=int(dc),
                                    client_packing=1, mxu_finish=mxu,
                                    rounds_per_dispatch=int(w), prefetch=False,
                                    tier=tier))
                    else:
                        for p in pack_factors:
                            for ad in agg_domains:
                                for ss in state_stores:
                                    for sw in state_windows:
                                        if coll == "hier" and (
                                                int(p) != 1 or ad != "f32"
                                                or sw is not None):
                                            # packing / wire-domain /
                                            # window store have no
                                            # hierarchical formulation
                                            continue
                                        tier = exe_tier
                                        if p != pack_factors[0]:
                                            tier = REASSOCIATING_TIER
                                        if ad != agg_domains[0]:
                                            # Quantized-domain statistics
                                            # reassociate f32 reductions AND
                                            # rank on the int8 grid — never a
                                            # default-tier handout.
                                            tier = REASSOCIATING_TIER
                                        if (ss != state_stores[0]
                                                or sw != state_windows[0]):
                                            # Store backends are bit-identical,
                                            # but reshaping the staging pipeline
                                            # is an opt-in probe (ISSUE 15), not
                                            # a default-tier handout.
                                            tier = REASSOCIATING_TIER
                                        pres = (prefetch_options
                                                if int(w) == 1
                                                and coll != "hier"
                                                else (False,))
                                        for pre in pres:
                                            plans.append(Plan(
                                                execution="dense",
                                                d_chunk=int(d_chunks[0]),
                                                client_packing=int(p),
                                                mxu_finish="",
                                                rounds_per_dispatch=int(w),
                                                prefetch=bool(pre),
                                                agg_domain=str(ad),
                                                state_store=str(ss),
                                                state_window=(None if sw is None
                                                              else int(sw)),
                                                mesh_shape=ms,
                                                collective=str(coll),
                                                tier=tier))
    if not allow_reassociating:
        plans = [p for p in plans if p.tier == DEFAULT_TIER]
    # Dedupe preserving order (e.g. a chunk ladder whose entries clamp
    # to the same effective width on a small model).
    plans = list(dict.fromkeys(plans))
    truncated = max(0, len(plans) - max_candidates)
    if truncated:
        plans = plans[:max_candidates]
    return PlanSpace(candidates=tuple(plans), truncated=truncated)


# ---------------------------------------------------------------------------
# trial harness
# ---------------------------------------------------------------------------


def timing_available() -> bool:
    """Whether wall-clock candidate trials mean anything here: the
    single-threaded CPU backend (tier-1, laptops) measures compile +
    interpreter noise, not the dispatch pipeline — selection there uses
    the deterministic heuristic ranking instead."""
    import jax

    try:
        return jax.default_backend() != "cpu"
    except Exception:
        return False


def timed_measure_fn(
    config,
    *,
    warmup: int = 1,
    reps: int = 3,
    clock: Optional[Callable[[], float]] = None,
    build: Optional[Callable[[Any], Any]] = None,
) -> Callable[[Plan], Optional[float]]:
    """Build the measured-trial function: plan -> median seconds per
    **FL round** (or ``None`` when the candidate fails to build).

    One ``train()`` dispatch advances ``plan.rounds_per_dispatch``
    rounds, so the raw dispatch median is divided by the window width —
    otherwise a w=8 scan-window candidate would measure ~8x a w=1
    candidate's dispatch and the tuner could never select a window.

    The candidate config is a copy with ``autotune`` disabled and the
    plan materialised, so it compiles through the PR 3 executable cache
    under the SAME fingerprint the winning plan's real run will use —
    the tuning compile is the run's compile.  ``clock`` is injectable
    (tests drive the timed path with a fake, deterministic clock);
    ``build`` defaults to ``candidate.build()``.
    """
    clock = clock or time.perf_counter
    if warmup < 0 or reps < 1:
        raise ValueError(f"need warmup >= 0, reps >= 1; got {warmup}/{reps}")

    def measure(plan: Plan) -> Optional[float]:
        cand = config.copy()
        cand.autotune = False
        cand.tuned_plan = None
        cand._autotune_windows = None
        apply_plan(cand, plan)
        algo = None
        try:
            algo = build(cand) if build is not None else cand.build()
            for _ in range(warmup):
                algo.train()
            times = []
            for _ in range(reps):
                t0 = clock()
                algo.train()
                times.append(clock() - t0)
        except Exception as exc:
            # A candidate that fails to build/run is ranked out, loudly:
            # silence here would hide a plan-space bug behind "the other
            # plan happened to win".
            warnings.warn(
                f"autotune candidate {plan.plan_id} failed and was "
                f"skipped: {type(exc).__name__}: {exc}", RuntimeWarning)
            return None
        finally:
            if algo is not None and callable(getattr(algo, "stop", None)):
                algo.stop()
        return float(statistics.median(times)) / max(
            1, int(plan.rounds_per_dispatch))

    return measure


def select_plan(
    space: PlanSpace,
    *,
    measure_fn: Optional[Callable[[Plan], Optional[float]]] = None,
) -> Tuple[Plan, Dict[str, Any]]:
    """Pick the winner from ``space``.

    With a ``measure_fn``: every candidate is measured, the fastest
    median wins (heuristic rank breaks exact ties, so selection is
    deterministic under an injected clock).  Without one — or when
    every measurement fails — the deterministic ranked heuristic wins:
    ``space.candidates[0]``, the plan the current resolution order
    produces, marked ``"mode": "heuristic"`` in the provenance.
    """
    timings: List[Optional[float]] = []
    if measure_fn is not None:
        for plan in space.candidates:
            timings.append(measure_fn(plan))
    else:
        timings = [None] * len(space.candidates)
    measured = [(t, i) for i, t in enumerate(timings) if t is not None]
    if measured:
        _, win = min(measured)
        mode, timed = "measured", True
    else:
        win, mode, timed = 0, "heuristic", False
    winner = space.candidates[win]
    provenance = {
        "mode": mode,                  # "measured" | "heuristic"
        "timed": timed,
        "cache_hit": False,
        "winner": winner.as_dict(),
        "winner_id": winner.plan_id,
        "candidates": [
            {"rank": i, "plan_id": p.plan_id, "tier": p.tier,
             "median_s": timings[i]}
            for i, p in enumerate(space.candidates)
        ],
        "truncated": space.truncated,
    }
    return winner, provenance


# ---------------------------------------------------------------------------
# persistent plan cache
# ---------------------------------------------------------------------------


def cache_key(
    config_fingerprint: str,
    tier: str = DEFAULT_TIER,
    device_kind: Optional[str] = None,
    jaxlib_version: Optional[str] = None,
) -> Dict[str, str]:
    """The plan-cache key: a plan tuned for one program on one device
    generation under one compiler is evidence about exactly that.  The
    config fingerprint already excludes ``seed`` (a seed grid shares
    one plan) and the autotune fields themselves; ``tier`` keeps a
    reassociating-tier winner from ever serving a default-tier run.
    """
    if device_kind is None:
        import jax

        try:
            dev = jax.devices()[0]
            device_kind = str(getattr(dev, "device_kind", None)
                              or dev.platform)
        except Exception:
            device_kind = "unknown"
    if jaxlib_version is None:
        try:
            import jaxlib

            jaxlib_version = str(jaxlib.__version__)
        except Exception:
            import jax

            jaxlib_version = str(getattr(jax, "__version__", "unknown"))
    return {
        "fingerprint": str(config_fingerprint),
        "tier": str(tier),
        "device_kind": device_kind,
        "jaxlib": jaxlib_version,
    }


class PlanCache:
    """On-disk winner cache: one JSON file per key under ``cache_dir``
    (``$BLADES_TPU_PLAN_CACHE_DIR`` or ``~/.cache/blades_tpu/plans``).

    Durability follows :func:`blades_tpu.faults.host.atomic_checkpoint`
    scaled down to a file: write ``<entry>.json.tmp``, fsync, one
    ``os.replace``.  A SIGKILL mid-write leaves either the previous
    entry or an orphaned ``.tmp`` that the next read deletes — never a
    torn entry.  Reads are corrupt-tolerant by contract: any
    undecodable / version-stale / key-mismatched / unparsable-plan file
    is treated as a miss (re-tune), never an exception.
    """

    def __init__(self, cache_dir=None):
        cache_dir = (cache_dir
                     or os.environ.get(ENV_CACHE_DIR)
                     or _DEFAULT_CACHE_DIR)
        self.dir = Path(cache_dir).expanduser()

    @staticmethod
    def digest(key: Dict[str, str]) -> str:
        return hashlib.sha1(
            json.dumps(key, sort_keys=True).encode()).hexdigest()

    def _path(self, key: Dict[str, str]) -> Path:
        return self.dir / f"{self.digest(key)}.json"

    def get(self, key: Dict[str, str]) -> Optional[Dict[str, Any]]:
        """The cached entry for ``key``, or ``None`` (miss / corrupt /
        stale / mismatched).  Also deletes this key's orphaned ``.tmp``
        (a writer killed before its ``os.replace``)."""
        path = self._path(key)
        tmp = path.with_name(path.name + ".tmp")
        if tmp.exists():
            try:
                tmp.unlink()
            except OSError:
                pass
        entry = self._read_entry(path)
        if entry is None:
            return None
        if entry.get("key") != key:
            # sha1 collision or a hand-moved file: the stored key is the
            # source of truth, the filename just locates it.
            return None
        return entry

    @staticmethod
    def _read_entry(path: Path) -> Optional[Dict[str, Any]]:
        try:
            entry = json.loads(path.read_text())
        except (OSError, json.JSONDecodeError, UnicodeDecodeError):
            return None
        if not isinstance(entry, dict):
            return None
        if entry.get("version") != PLAN_CACHE_VERSION:
            return None
        try:
            Plan.from_dict(entry.get("plan"))
        except (ValueError, TypeError):
            return None
        return entry

    def put(self, key: Dict[str, str], plan: Plan,
            provenance: Optional[Dict[str, Any]] = None) -> Optional[str]:
        """Persist a winner atomically; returns the entry path, or
        ``None`` when the filesystem refuses (an unwritable cache must
        degrade to tune-per-process, never fail the trial)."""
        entry = {
            "version": PLAN_CACHE_VERSION,
            "key": dict(key),
            "plan": plan.as_dict(),
            "provenance": dict(provenance or {}),
            "created_unix": time.time(),  # blades-lint: disable=trace-discipline — wall-clock cache metadata stamp, not a duration measurement
        }
        path = self._path(key)
        tmp = path.with_name(path.name + ".tmp")
        try:
            self.dir.mkdir(parents=True, exist_ok=True)
            with open(tmp, "w") as f:
                json.dump(entry, f, indent=2, sort_keys=True)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except OSError as exc:
            warnings.warn(f"plan cache write failed ({exc}); the plan "
                          "will be re-tuned next process", RuntimeWarning)
            try:
                tmp.unlink()
            except OSError:
                pass
            return None
        return str(path)

    def entries(self) -> List[Tuple[str, Optional[Dict[str, Any]]]]:
        """Every ``(digest, entry-or-None)`` in the cache dir, sorted;
        ``None`` marks a file the tolerant reader rejected (corrupt or
        stale-version) — surfaced so ``tools/show_plan.py`` can report
        rather than hide them."""
        if not self.dir.is_dir():
            return []
        out = []
        for p in sorted(self.dir.glob("*.json")):
            out.append((p.stem, self._read_entry(p)))
        return out

    def invalidate(self, digest: Optional[str] = None) -> List[str]:
        """Delete one entry by digest, or every entry (and orphaned
        ``.tmp``) when ``digest`` is None.  Returns the removed names."""
        if not self.dir.is_dir():
            return []
        removed = []
        pats = ([f"{digest}.json", f"{digest}.json.tmp"] if digest
                else ["*.json", "*.json.tmp"])
        for pat in pats:
            for p in sorted(self.dir.glob(pat)):
                try:
                    p.unlink()
                    removed.append(p.name)
                except OSError:
                    pass
        return removed
