"""Buffer donation + AOT executable cache for the round pipeline.

Every sweep trial used to pay its own ``jit`` trace + XLA compile of the
round program even when the grid only varies knobs that never reach the
program as constants (the seed grid is the canonical case: seeds change
data *values* and PRNG key *values* — both runtime arguments — and
nothing else).  :func:`cached_jit` makes that cost once-per-geometry:
the compiled executable is keyed on

    (caller key = role + static config fingerprint,
     donate_argnums,
     pytree structure + abstract shapes/dtypes of the arguments,
     the device set)

and shared process-wide, so a grid of N identically-shaped trials
lowers and compiles exactly once.  Hit/miss counts are kept globally
(:func:`cache_stats`) and per wrapper (``CachedFunction.hits`` /
``.misses``) so sweeps can surface them through the obs pipeline.

The *caller key* must fingerprint every value the traced program bakes
in as a constant (aggregator trim counts, server lr, DP thresholds,
adversary scale, ...).  :func:`fingerprint` hashes a JSON-able static
config; callers holding baked-in *arrays* (FLTrust's trusted root data
is the one case in this codebase) must digest the bytes into the key —
see :meth:`blades_tpu.algorithms.fedavg.Fedavg` — or skip the cache.

Donation rides the same wrapper: ``donate_argnums`` is recorded in the
lowering, so a cached executable invalidates its donated inputs exactly
like ``jax.jit(fn, donate_argnums=...)`` would.  The donated
``RoundState`` is what halves peak HBM for the largest tensors in the
system (the stacked client optimizer states and, through the streamed
path's own donation chain, the ``(n, d)`` update buffer).

:func:`enable_persistent_compilation_cache` wires JAX's on-disk
compilation cache (``jax_compilation_cache_dir``) underneath: the
in-process cache skips *tracing and dispatch table misses* within a
sweep; the persistent cache skips *XLA itself* across sweeps.
"""

from __future__ import annotations

import hashlib
import json
import threading
from typing import Any, Callable, Dict, Optional, Sequence, Tuple

import jax

_lock = threading.Lock()
# (caller_key, donate, avals_key, devices_key) -> compiled executable
_executables: Dict[Tuple, Any] = {}
# role (the first element of the caller key) -> {"hits": n, "misses": n}
_stats: Dict[str, Dict[str, int]] = {}


def fingerprint(static_config: Any) -> str:
    """Stable digest of a JSON-able static-config object (dicts, lists,
    scalars; unknown types stringify).  Two configs with equal
    fingerprints MUST lower to byte-identical programs at equal argument
    shapes — that is the caller's contract, not something this function
    can check."""
    blob = json.dumps(static_config, sort_keys=True, default=str)
    return hashlib.sha1(blob.encode()).hexdigest()


def _aval_key(leaf) -> Tuple:
    aval = jax.api_util.shaped_abstractify(leaf)
    return (aval.shape, str(aval.dtype), bool(getattr(aval, "weak_type", False)))


def clear_cache() -> None:
    """Drop every cached executable and reset the counters (tests)."""
    with _lock:
        _executables.clear()
        _stats.clear()


def cache_stats() -> Dict[str, Any]:
    """Process-wide compile-cache counters: total hits/misses/entries
    plus a per-role breakdown (role = the first element of the caller
    key, e.g. ``"step"`` for the round program)."""
    with _lock:
        by_role = {r: dict(c) for r, c in _stats.items()}
        return {
            "hits": sum(c["hits"] for c in by_role.values()),
            "misses": sum(c["misses"] for c in by_role.values()),
            "entries": len(_executables),
            "by_role": by_role,
        }


class CachedFunction:
    """``jax.jit(fn, donate_argnums=...)`` with the compiled executable
    shared process-wide by ``(key, argument avals)``.

    The wrapper compiles lazily on first call (``lower().compile()``)
    and thereafter dispatches straight to the executable — including
    executables compiled by a *different* ``CachedFunction`` whose key
    and argument geometry match (that is the cross-trial sharing).
    ``hits``/``misses`` count this wrapper's own lookups; the global
    tallies aggregate by role in :func:`cache_stats`.
    """

    def __init__(
        self,
        fn: Callable,
        key: Tuple,
        donate_argnums: Sequence[int] = (),
    ):
        self._fn = fn
        self._key = tuple(key)
        self._role = str(key[0]) if key else "anon"
        self._donate = tuple(donate_argnums)
        self.hits = 0
        self.misses = 0

    # -- key --------------------------------------------------------------

    _devices_key: Optional[Tuple] = None  # class-level memo (stable per process)

    def _lookup_key(self, args) -> Tuple:
        # Built per dispatch (argument geometry may legitimately change
        # between calls), so keep it lean: the device set is memoized
        # process-wide — jax.devices() plus len(devices) str() calls per
        # round is pure waste in the loop this layer exists to thin out.
        if CachedFunction._devices_key is None:
            CachedFunction._devices_key = tuple(str(d) for d in jax.devices())
        leaves, treedef = jax.tree.flatten(args)
        avals = tuple(_aval_key(l) for l in leaves)
        return (self._key, self._donate, str(treedef), avals,
                CachedFunction._devices_key)

    # -- call -------------------------------------------------------------

    def __call__(self, *args):
        k = self._lookup_key(args)
        with _lock:
            compiled = _executables.get(k)
            tally = _stats.setdefault(self._role, {"hits": 0, "misses": 0})
            if compiled is not None:
                tally["hits"] += 1
                self.hits += 1
        if compiled is None:
            compiled = self.lower(*args).compile()
            with _lock:
                # First writer wins on a race; both compiled the same
                # program, so either executable is correct.
                compiled = _executables.setdefault(k, compiled)
                _stats[self._role]["misses"] += 1
                self.misses += 1
        return compiled(*args)

    def lower(self, *args):
        """Fresh lowering (used by XLA cost analysis); does not touch
        the executable cache."""
        return jax.jit(self._fn, donate_argnums=self._donate).lower(*args)

    @property
    def stats(self) -> Dict[str, int]:
        return {"hits": self.hits, "misses": self.misses}


def cached_jit(
    fn: Callable,
    *,
    key: Tuple,
    donate_argnums: Sequence[int] = (),
) -> CachedFunction:
    """Wrap ``fn`` in a :class:`CachedFunction`.

    ``key`` must start with a short role string (``"step"``,
    ``"evaluate"``, ...) and contain (or derive from) a
    :func:`fingerprint` of every static value the traced program bakes
    in.  Equal keys + equal argument geometry ⇒ the executable is
    reused verbatim.
    """
    return CachedFunction(fn, key=key, donate_argnums=donate_argnums)


_persistent_dir: Optional[str] = None


def enable_persistent_compilation_cache(
    cache_dir: Optional[str] = None,
) -> Optional[str]:
    """Point JAX's persistent compilation cache at ``cache_dir`` (or
    ``$BLADES_TPU_COMPILE_CACHE_DIR``), so a repeat sweep's XLA work is
    a disk read.  Thresholds are dropped to zero — FL round programs on
    CPU can compile in under the 1 s default and would otherwise never
    be cached.  Returns the directory in effect, or ``None`` when no
    directory is configured.  Idempotent; never raises (an old jax
    without a knob just skips it)."""
    global _persistent_dir
    import os

    cache_dir = cache_dir or os.environ.get("BLADES_TPU_COMPILE_CACHE_DIR")
    if not cache_dir:
        return _persistent_dir
    if _persistent_dir == cache_dir:
        return _persistent_dir
    os.makedirs(cache_dir, exist_ok=True)
    for name, value in (
        ("jax_compilation_cache_dir", cache_dir),
        ("jax_persistent_cache_min_compile_time_secs", 0.0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(name, value)
        except Exception:  # knob absent in this jax — best-effort wiring
            pass
    _persistent_dir = cache_dir
    return _persistent_dir
