"""Round-pipeline performance layer (host side).

Three coupled pieces that make the *orchestration around* the jitted
round as fast as the round itself (ByzFL arXiv:2505.24802 and
ring-allreduce Byzantine FL arXiv:2501.17392 both locate the
robust-FL throughput ceiling here, not in the defense kernels):

- :mod:`blades_tpu.perf.compile_cache` — buffer donation + an
  in-process AOT executable cache (``jit(...).lower().compile()`` keyed
  on abstract shapes/dtypes + a static round-config fingerprint) shared
  across sweep trials and lane groups, plus wiring for JAX's persistent
  compilation cache so repeat sweeps skip XLA entirely.
- :mod:`blades_tpu.perf.async_metrics` — batched ``device_get`` of
  per-round scalar metrics every ``metrics_every`` rounds (flushed at
  checkpoint / preemption / fault boundaries so the chaos layer's
  replay guarantees hold).
- :mod:`blades_tpu.data.prefetch` (sibling) — double-buffered
  device staging of the next round's per-client batches.
- :mod:`blades_tpu.perf.autotune` — the execution autotuner: measured
  plan selection over the round pipeline's perf levers (execution
  path, streamed ``d_chunk``, lane packing, MXU finish, scan windows,
  prefetch) with a persistent on-disk plan cache.  See the README
  "Execution autotuner" section.
"""

from blades_tpu.perf.async_metrics import flush_rows  # noqa: F401
from blades_tpu.perf.autotune import (  # noqa: F401
    Plan,
    PlanCache,
    PlanSpace,
    apply_plan,
    enumerate_plans,
    select_plan,
    timed_measure_fn,
)
from blades_tpu.perf.compile_cache import (  # noqa: F401
    CachedFunction,
    cache_stats,
    cached_jit,
    clear_cache,
    enable_persistent_compilation_cache,
    fingerprint,
)
