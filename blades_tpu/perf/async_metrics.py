"""Batched device→host metric fetches for the training loop.

The sequential sweep used to block on ``float(metric)`` for every round
— one host↔device round trip per FL round, which through a remote
accelerator relay costs more than the round itself.  The async loop
instead carries *deferred rows*: result dicts whose scalar metrics are
still device arrays under the ``_device_metrics`` key, accumulated and
fetched in ONE ``jax.device_get`` per flush.

Flush points are part of the durability contract, not an optimization
detail: rows must be on disk before any checkpoint that covers them
(otherwise a crash after the checkpoint leaves a round-sequence gap
that ``verify_result_rounds`` rejects), so the sweep flushes

- every ``metrics_every`` buffered rows,
- before every checkpoint save and before the simulated-preemption
  hook fires (the chaos layer's widest kill window),
- at loop exit, and best-effort on the failure path (a row whose
  device values are poisoned is dropped; its rounds replay
  deterministically from the restored checkpoint).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax

#: Key under which a deferred row carries its un-fetched device metrics.
DEVICE_METRICS_KEY = "_device_metrics"


def flush_rows(
    rows: List[Dict],
    finalize: Optional[Callable[[Dict], Dict]] = None,
) -> List[Dict]:
    """Fetch every pending device value across ``rows`` in one
    ``device_get``, then finalize each row (in order) into its host
    form.  Rows without deferred metrics pass through ``finalize``
    unchanged.  Returns the finalized rows; ``rows`` is not mutated
    beyond replacing the deferred values with their fetched forms."""
    pending = [r.get(DEVICE_METRICS_KEY) for r in rows]
    if any(p is not None for p in pending):
        fetched = jax.device_get(pending)
        for row, host in zip(rows, fetched):
            if host is not None:
                row[DEVICE_METRICS_KEY] = host
    if finalize is None:
        return list(rows)
    return [finalize(r) for r in rows]
