"""Peer graphs for the decentralized gossip round (ROADMAP item 4).

A :class:`TopologyConfig` names a static peer graph over the federation's
nodes (ring / torus / k-regular circulant / seeded Erdős–Rényi /
complete), builds its adjacency as a plain numpy matrix, and derives a
symmetric doubly-stochastic mixing matrix from it (Metropolis–Hastings or
max-degree/uniform weights — both classic gossip-averaging choices,
e.g. Boyd et al. "Randomized gossip algorithms").  Everything here is
HOST-side, trace-time-static provenance: the gossip round program
(:mod:`blades_tpu.topology.gossip`) closes over the tables this module
emits, the way the hierarchical round closes over its bucket geometry.

Determinism contract: every builder is a pure function of the config
fields (``graph_seed`` drives the one random family), so two processes
with the same :class:`TopologyConfig` trace the identical round program —
the property checkpoints and ``tools/replay_round.py`` rely on.

The one load-bearing ordering convention lives in
:meth:`TopologyConfig.neighbor_tables`: each node's neighborhood slots
(its neighbors PLUS itself) are sorted by **ascending global node index**,
padded to the max closed-neighborhood size with duplicates of the node's
own index.  On the complete graph every node's slot row is therefore
exactly ``0..n-1`` — the same row order as the centralized ``(n, d)``
update matrix — which is what makes the complete-graph + Mean gossip
round bit-identical (tolerance ZERO) to the dense server round.
"""

from __future__ import annotations

import dataclasses
from typing import Tuple

import numpy as np

GRAPHS = ("ring", "torus", "kregular", "erdos", "complete")
MIXINGS = ("metropolis", "uniform")


@dataclasses.dataclass(frozen=True)
class NeighborTables:
    """Static per-node neighborhood tables the gossip program closes over.

    nbr_idx: ``(n, k1)`` int32 — node ``i``'s closed neighborhood
        (neighbors + itself) sorted by ASCENDING global index, padded to
        ``k1 = max_i (deg_i + 1)`` with copies of ``i`` (self-duplication
        padding: a pad slot aggregates the node's own row, the
        static-shape analogue of a masked row).
    valid: ``(n, k1)`` bool — True on the real (non-pad) slots.
    w_slot: ``(n, k1)`` float32 — the mixing weight ``W[i, nbr_idx[i,s]]``
        for valid NON-self slots, 0 elsewhere.  The self weight never
        appears: mixing runs in deviation form
        ``θ_i + Σ_s w_slot[i,s] (θ_{nbr} − θ_i)``, where the self/pad
        deviations are exact zeros.
    self_slot: ``(n,)`` int32 — the slot holding ``i`` itself.
    """

    nbr_idx: np.ndarray
    valid: np.ndarray
    w_slot: np.ndarray
    self_slot: np.ndarray


def _ring(n: int) -> np.ndarray:
    a = np.zeros((n, n), bool)
    idx = np.arange(n)
    a[idx, (idx + 1) % n] = True
    a[(idx + 1) % n, idx] = True
    return a


def _torus(n: int) -> np.ndarray:
    # Largest divisor <= sqrt(n) gives the squarest (rows, cols) grid.
    rows = max(r for r in range(1, int(np.sqrt(n)) + 1) if n % r == 0)
    cols = n // rows
    if rows < 2 or cols < 2:
        raise ValueError(
            f"torus needs a 2-D grid: num_nodes={n} only factors as "
            f"{rows}x{cols} — use a composite node count (>= 4, not "
            "prime), or a ring/kregular graph")
    a = np.zeros((n, n), bool)
    for i in range(n):
        r, c = divmod(i, cols)
        for rr, cc in (((r + 1) % rows, c), ((r - 1) % rows, c),
                       (r, (c + 1) % cols), (r, (c - 1) % cols)):
            j = rr * cols + cc
            if j != i:
                a[i, j] = a[j, i] = True
    return a


def _kregular(n: int, k: int) -> np.ndarray:
    # Circulant graph: each node links to its k//2 nearest on each side.
    if k % 2 or not 2 <= k < n:
        raise ValueError(
            f"kregular degree k={k} must be even with 2 <= k < "
            f"num_nodes={n} (circulant construction links k/2 "
            "neighbors per side)")
    a = np.zeros((n, n), bool)
    idx = np.arange(n)
    for off in range(1, k // 2 + 1):
        a[idx, (idx + off) % n] = True
        a[(idx + off) % n, idx] = True
    return a


def _erdos(n: int, p: float, seed: int) -> np.ndarray:
    # Seeded G(n, p) PLUS a ring backbone: gossip over a disconnected
    # graph never reaches consensus, so connectivity is guaranteed by
    # construction and the spectral gap reports how well-mixed the draw
    # actually is.
    if not 0.0 <= p <= 1.0:
        raise ValueError(f"erdos edge probability p={p} must be in [0, 1]")
    rng = np.random.default_rng(seed)
    u = rng.random((n, n))
    a = np.triu(u < p, k=1)
    a = a | a.T | _ring(n)
    np.fill_diagonal(a, False)
    return a


@dataclasses.dataclass(frozen=True)
class TopologyConfig:
    """Frozen spec of the gossip peer graph + mixing weights.

    graph: one of :data:`GRAPHS`.
    num_nodes: federation size (nodes == clients on the gossip path).
    k: circulant degree for ``graph="kregular"`` (even, ``2 <= k < n``).
    p: edge probability for ``graph="erdos"`` (a ring backbone keeps the
        draw connected).
    graph_seed: the Erdős–Rényi draw's seed — part of the config, so the
        topology is replayable provenance, never ambient randomness.
    mixing: ``"metropolis"`` (Metropolis–Hastings weights
        ``1 / (1 + max(deg_i, deg_j))``) or ``"uniform"`` (max-degree
        weights ``1 / (1 + max_deg)``) — both symmetric doubly-stochastic
        with non-negative self weights.
    """

    graph: str = "ring"
    num_nodes: int = 8
    k: int = 4
    p: float = 0.3
    graph_seed: int = 0
    mixing: str = "metropolis"

    def __post_init__(self):
        if self.graph not in GRAPHS:
            raise ValueError(
                f"unknown topology graph {self.graph!r}; use one of "
                f"{GRAPHS}")
        if self.mixing not in MIXINGS:
            raise ValueError(
                f"unknown mixing scheme {self.mixing!r}; use one of "
                f"{MIXINGS}")
        if not isinstance(self.num_nodes, int) or self.num_nodes < 2:
            raise ValueError(
                f"topology needs num_nodes >= 2, got {self.num_nodes!r}")
        # Build once now so a bad (graph, knob) pair fails at config
        # time, not at trace time — the faults/codec fail-fast discipline.
        self.adjacency()

    # -- graph ---------------------------------------------------------------

    def adjacency(self) -> np.ndarray:
        """Symmetric ``(n, n)`` bool adjacency, no self loops."""
        n = self.num_nodes
        if self.graph == "ring":
            return _ring(n)
        if self.graph == "torus":
            return _torus(n)
        if self.graph == "kregular":
            return _kregular(n, self.k)
        if self.graph == "erdos":
            return _erdos(n, self.p, self.graph_seed)
        a = np.ones((n, n), bool)
        np.fill_diagonal(a, False)
        return a

    def mixing_matrix(self) -> np.ndarray:
        """Symmetric doubly-stochastic ``(n, n)`` float64 mixing matrix."""
        a = self.adjacency()
        deg = a.sum(axis=1)
        if self.mixing == "metropolis":
            w = np.where(a, 1.0 / (1.0 + np.maximum(deg[:, None],
                                                    deg[None, :])), 0.0)
        else:
            w = np.where(a, 1.0 / (1.0 + deg.max()), 0.0)
        np.fill_diagonal(w, 1.0 - w.sum(axis=1))
        return w

    @property
    def spectral_gap(self) -> float:
        """``1 - max(|λ2|, |λn|)`` of the mixing matrix — the consensus
        contraction rate, reported as provenance on every gossip row."""
        lam = np.linalg.eigvalsh(self.mixing_matrix())
        lam = np.sort(np.abs(lam))[::-1]
        return float(1.0 - (lam[1] if lam.size > 1 else 0.0))

    # -- tables --------------------------------------------------------------

    def neighbor_tables(self) -> NeighborTables:
        """The static slot tables the gossip program closes over — see
        :class:`NeighborTables` for the ascending-global-index ordering
        contract the bit-identity pin rests on."""
        a = self.adjacency()
        w = self.mixing_matrix()
        n = self.num_nodes
        closed = [np.flatnonzero(a[i] | (np.arange(n) == i))
                  for i in range(n)]
        k1 = max(len(c) for c in closed)
        nbr = np.empty((n, k1), np.int32)
        valid = np.zeros((n, k1), bool)
        wslot = np.zeros((n, k1), np.float32)
        self_slot = np.empty((n,), np.int32)
        for i, c in enumerate(closed):
            d_i = len(c)
            nbr[i, :d_i] = c
            nbr[i, d_i:] = i
            valid[i, :d_i] = True
            wslot[i, :d_i] = np.where(c == i, 0.0, w[i, c])
            self_slot[i] = int(np.flatnonzero(c == i)[0])
        return NeighborTables(nbr_idx=nbr, valid=valid, w_slot=wslot,
                              self_slot=self_slot)

    def provenance(self) -> dict:
        """The host-side stamps every gossip metrics row carries."""
        a = self.adjacency()
        return {
            "topology": self.graph,
            "graph_seed": int(self.graph_seed),
            "spectral_gap": self.spectral_gap,
            "num_nodes": int(self.num_nodes),
            "num_edges": int(a.sum() // 2),
            "max_degree": int(a.sum(axis=1).max()),
            "mixing": self.mixing,
        }


def get_topology(spec, num_nodes: int) -> TopologyConfig:
    """Resolve a topology from a name / dict / instance (the
    ``get_adversary`` resolution shape), pinning ``num_nodes``."""
    if isinstance(spec, TopologyConfig):
        return spec
    if spec is None:
        spec = {}
    if isinstance(spec, str):
        spec = {"graph": spec}
    return TopologyConfig(num_nodes=num_nodes, **dict(spec))
