"""Decentralized gossip round: peer-graph federation, no central server.

The FIFTH round path (after dense / streamed / dsharded / hier), and the
first with no coordinator: every node holds its OWN params replica,
trains locally, exchanges models with its graph neighborhood
(:mod:`blades_tpu.topology.graph`), robust-aggregates its neighbors'
updates with the per-node geometry of the existing aggregator suite, and
mixes params with doubly-stochastic gossip weights — one jitted
``shard_map`` program per round over the 1-D clients mesh, each chip
advancing its block of node replicas.

Round anatomy (all inside one trace)::

    train    θ_i --local rounds--> u_i                 (per node, vmapped)
    gather   all_gather u, ravel(θ), losses            (counted ICI)
    forge    dense-order health -> DP -> adversary     (replicated)
    select   per-node (k1, d) neighborhood matrices    (static slot tables)
    mix      θ̄_i = θ_i + Σ_s w[i,s] (θ_nbr − θ_i)     (deviation form)
    agg      per-node robust aggregate + optimizer     (vmapped server step)

RNG discipline — identical to :mod:`blades_tpu.parallel.hier`: the round
key splits 5 ways globally, per-client keys split to the TRUE count,
padded, sliced per chip.  Every node therefore draws the same batches
and local rounds as the single-chip dense program; on the COMPLETE graph
each node's neighborhood slots are ``0..n-1`` in ascending global order
(:meth:`TopologyConfig.neighbor_tables`), so its matrix IS the dense
matrix, deviation-form mixing over identical replicas is exactly the
identity, and complete-graph + Mean is pinned **bit-identical** to
centralized FedAvg at tolerance ZERO (tests/test_topology.py).

Threat model: update-forging adversaries run in the same dense order and
see the full matrix (omniscience convention); a ``topology_scoped``
adversary (:mod:`blades_tpu.adversaries.topology_attacks`) additionally
restricts WHICH receivers see forged rows — per-receiver matrices via a
static forged/clean row-select, out-edge poisoning and eclipse targeting.

Partition tolerance (``faults=`` with a dropout process): symmetric edge
dropout realized purely in ``(fault_seed, round)``
(``fold_in(round_key, EDGE_FOLD)``), dropped edges zero their mixing
weight and are replaced by the node's OWN row in its matrix; a node
whose live neighborhood falls below its aggregator's breakdown bound
(:func:`blades_tpu.ops.aggregators.breakdown_min_rows`) degrades LOUDLY
to self-trust (aggregate := own update) and is counted in the
``num_partitioned_nodes`` metric.  ``faults.inject`` is never called:
node-lane dropout/stragglers/corruption are server-path processes.

ICI accounting: every collective is counted on the
:class:`~blades_tpu.parallel.streamed_geometry.PassRecorder` and the
totals reconcile event-by-event against
:func:`blades_tpu.parallel.comm_model.gossip_round_volumes` in both
directions; the per-round ``gossip_ici_bytes`` metric is stamped
trace-time like ``ici_bytes`` on the hier path.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from blades_tpu.core.round import FedRound, RoundState
from blades_tpu.core.server import ServerState
from blades_tpu.data.sampler import sample_client_batches_with_keys
from blades_tpu.ops.aggregators import BREAKDOWN_MIN_ROWS
from blades_tpu.parallel.compat import shard_map
from blades_tpu.parallel.mesh import (
    CLIENTS_AXIS,
    D_AXIS,
    client_axis_sharding,
    pad_to_multiple,
)
from blades_tpu.parallel.streamed_geometry import PassRecorder
from blades_tpu.topology.graph import TopologyConfig
from blades_tpu.utils.tree import ravel_fn

#: Fold applied to the fault round key for the edge-dropout draw — a
#: dedicated stream so node-lane fault processes (server paths) and edge
#: faults (this path) never alias even under the same fault seed.
EDGE_FOLD = 0xED6E


def _check_supported(fr: FedRound, topo: TopologyConfig, mesh: Mesh) -> None:
    axes = dict(mesh.shape)
    if int(axes.get(D_AXIS, 1)) != 1:
        raise ValueError(
            "gossip × 2-D mesh_shape is unsupported — the gossip round "
            "shards nodes over the 1-D clients mesh; drop mesh_shape")
    if fr.packing is not None:
        raise ValueError("gossip × packing is unsupported — resolve "
                         "packing off for the gossip path")
    if fr.codec is not None:
        raise ValueError("gossip × codec is unsupported — the wire codec "
                         "runs on server-bound updates, which do not "
                         "exist here")
    if fr.agg_domain != "f32":
        raise ValueError(
            f"gossip × agg_domain={fr.agg_domain!r} is unsupported — "
            "per-node neighborhood aggregation is f32-domain only")
    if fr.stateless_clients:
        raise ValueError("gossip × stateless clients (window=0) is "
                         "unsupported")
    if fr.forensics:
        raise ValueError("gossip × forensics is unsupported — per-lane "
                         "diagnostics assume the single server matrix")
    if fr.faults is not None:
        if fr.faults.needs_stale_buffer:
            raise ValueError(
                "gossip × straggler faults is unsupported — the stale "
                "ring buffer is a server-path process; gossip faults are "
                "EDGE dropout (use dropout_rate/dropout_schedule)")
        if fr.faults.corrupt_rate > 0.0:
            raise ValueError(
                "gossip × corruption faults is unsupported — lane "
                "corruption models server-bound transfers; gossip "
                "faults are EDGE dropout")
    if fr.num_clients is not None and int(fr.num_clients) != topo.num_nodes:
        raise ValueError(
            f"topology num_nodes={topo.num_nodes} != num_clients="
            f"{fr.num_clients}: on the gossip path every client IS a "
            "node — size the topology to the federation")
    k1 = topo.neighbor_tables().nbr_idx.shape[1]
    name = fr.server.aggregator.name
    if name in BREAKDOWN_MIN_ROWS:
        a, b = BREAKDOWN_MIN_ROWS[name]
        f_cfg = int(getattr(fr.server.aggregator, "num_byzantine", 0) or 0)
        need = a * f_cfg + b
        if need > k1:
            raise ValueError(
                f"gossip × {name}(num_byzantine={f_cfg}) needs "
                f"neighborhood matrices of >= {need} rows, but graph="
                f"{topo.graph!r} gives max closed-neighborhood size "
                f"{k1} — densify the graph (kregular with larger k, "
                "complete) or pick an aggregator with a smaller "
                "breakdown bound")


def _degradation_bound(fr: FedRound) -> Tuple[int, int]:
    """Static ``(a, b)`` of the aggregator's breakdown line ``a*f + b``
    (self-trust below it); unknown aggregators never degrade."""
    return BREAKDOWN_MIN_ROWS.get(fr.server.aggregator.name, (0, 1))


def gossip_step(
    fr: FedRound,
    mesh: Mesh,
    topo: TopologyConfig,
    recorder: Optional[PassRecorder] = None,
) -> Callable:
    """Gossip shard_map round over the 1-D ``(clients,)`` mesh.

    Returns ``(step, recorder)`` where ``step(state, x, y, lengths,
    malicious, key) -> (state, metrics)``: the STACKED per-node server
    state (leading axis ``n_pad``) and client state shard ``P(clients)``
    (:func:`gossip_federation` builds the placement), ``malicious``
    REPLICATED and UNPADDED, key replicated.  Metrics gain trace-time
    ``gossip_ici_bytes`` plus the consensus/partition sensors;
    ``recorder`` holds the per-collective ``ici_events`` for
    reconciliation against the comm model.
    """
    _check_supported(fr, topo, mesh)
    rec = recorder if recorder is not None else PassRecorder()
    c = int(dict(mesh.shape)[CLIENTS_AXIS])
    tabs = topo.neighbor_tables()
    n_real = topo.num_nodes
    k1 = tabs.nbr_idx.shape[1]
    a_bd, b_bd = _degradation_bound(fr)
    adv = fr.adversary
    topo_scoped = getattr(adv, "topology_scoped", False)
    if topo_scoped:
        recv_np = adv.receiver_mask(topo.adjacency())
    else:
        # Non-topology adversaries broadcast: every receiver sees the
        # forged matrix — exactly the dense threat model, which is what
        # keeps the complete-graph round bit-identical to centralized.
        recv_np = np.ones((n_real, n_real), bool)

    state_spec = RoundState(server=P(CLIENTS_AXIS), client_opt=P(CLIENTS_AXIS))
    data_spec = P(CLIENTS_AXIS)

    @partial(
        shard_map,
        mesh=mesh,
        in_specs=(state_spec, data_spec, data_spec, data_spec, P(), P()),
        out_specs=(state_spec, P()),
        check_vma=False,
    )
    def _step(state: RoundState, data_x, data_y, lengths, malicious, key):
        n_local = data_x.shape[0]
        n_pad = c * n_local
        if n_real > n_pad:
            raise ValueError(
                f"topology num_nodes={n_real} incompatible with {c} "
                f"chips × {n_local} lanes")

        # Static slot tables, padded to the mesh-padded node count: a
        # ghost node's slots all point at itself with zero weight.
        ghost = n_pad - n_real
        nbr_np = tabs.nbr_idx
        valid_np, w_np = tabs.valid, tabs.w_slot
        recv_full = recv_np
        if ghost:
            gh = np.repeat(np.arange(n_real, n_pad, dtype=np.int32)[:, None],
                           k1, axis=1)
            nbr_np = np.concatenate([nbr_np, gh], axis=0)
            valid_np = np.concatenate(
                [valid_np, np.zeros((ghost, k1), bool)], axis=0)
            w_np = np.concatenate(
                [w_np, np.zeros((ghost, k1), np.float32)], axis=0)
        recv_full = np.zeros((n_pad, n_pad), bool)
        recv_full[:n_real, :n_real] = recv_np
        nbr_all = jnp.asarray(nbr_np)
        valid_all = jnp.asarray(valid_np)
        w_all = jnp.asarray(w_np)
        recv_all = jnp.asarray(recv_full)

        # DENSE key discipline (see blades_tpu/parallel/hier.py): global
        # 5-way split, per-client keys split to the TRUE count, padded,
        # sliced per chip.
        k_sample, k_train, k_adv, k_agg, k_dp = jax.random.split(key, 5)
        sample_keys = jax.random.split(k_sample, n_real)
        train_keys = jax.random.split(k_train, n_real)
        if ghost:
            sample_keys = jnp.pad(sample_keys, ((0, ghost), (0, 0)))
            train_keys = jnp.pad(train_keys, ((0, ghost), (0, 0)))
        start = lax.axis_index(CLIENTS_AXIS) * n_local
        local_sample = lax.dynamic_slice_in_dim(sample_keys, start, n_local, 0)
        local_train = lax.dynamic_slice_in_dim(train_keys, start, n_local, 0)
        mal_pad = jnp.pad(malicious, (0, ghost)) if ghost else malicious
        mal_local = lax.dynamic_slice_in_dim(mal_pad, start, n_local, 0)
        gidx = start + jnp.arange(n_local)

        with jax.named_scope("blades/sample"):
            bx, by = sample_client_batches_with_keys(
                local_sample, data_x, data_y, lengths,
                fr.batch_size, fr.num_batches_per_round,
            )
        hooks = fr._hooks()
        srv = state.server  # stacked ServerState, leading axis n_local
        example = jax.tree.map(lambda p: p[0], srv.params)
        ravel, unravel, _d = ravel_fn(example)

        # Per-node local training: unlike every server path, params are
        # MAPPED — each node trains from its own replica.
        def one_node(p, o, cbx, cby, ck, m):
            return fr.task.local_round(p, o, cbx, cby, ck, m, *hooks)

        with jax.named_scope("blades/step"):
            upd_local, client_opt, losses_local = jax.vmap(one_node)(
                srv.params, state.client_opt, bx, by, local_train, mal_local)
        d_full = upd_local.shape[1]
        th_local = jax.vmap(ravel)(srv.params)

        # Neighborhood exchange: the ONLY collectives of the round, all
        # counted with the comm-model (kind, payload) vocabulary.
        with jax.named_scope("blades/gather"):
            updates = lax.all_gather(upd_local, CLIENTS_AXIS, axis=0,
                                     tiled=True)
            rec.count_ici("updates_gather", "all_gather", n_pad * d_full * 4, c)
            theta = lax.all_gather(th_local, CLIENTS_AXIS, axis=0, tiled=True)
            rec.count_ici("params_gather", "all_gather", n_pad * d_full * 4, c)
            losses = lax.all_gather(losses_local, CLIENTS_AXIS, axis=0,
                                    tiled=True)
            rec.count_ici("losses_gather", "all_gather", n_pad * 4, c)

        # Replicated dense-order preprocessing over the REAL rows:
        # health -> DP -> forge, exactly finish_dense's sequence.
        u_r = updates[:n_real]
        healthy = None
        if fr.health_check:
            from blades_tpu.core.health import sanitize_updates

            u_r, healthy = sanitize_updates(u_r)
        u_r = fr.apply_dp(u_r, k_dp)
        clean = u_r
        forged = clean
        if adv is not None and hasattr(adv, "on_updates_ready"):
            with jax.named_scope("blades/forge"):
                forged = adv.on_updates_ready(
                    u_r, malicious, k_adv,
                    aggregator=fr.server.aggregator,
                    global_params=unravel(theta[0]),
                )
        zpad = ((0, ghost), (0, 0))
        clean_pad = jnp.pad(clean, zpad) if ghost else clean
        forged_pad = jnp.pad(forged, zpad) if ghost else forged

        # This chip's slice of the static tables.
        nbr_c = lax.dynamic_slice_in_dim(nbr_all, start, n_local, 0)
        valid_c = lax.dynamic_slice_in_dim(valid_all, start, n_local, 0)
        w_c = lax.dynamic_slice_in_dim(w_all, start, n_local, 0)
        recv_c = lax.dynamic_slice_in_dim(recv_all, start, n_local, 0)
        is_self = nbr_c == gidx[:, None]

        # Per-receiver neighborhood matrices: slot s of node i holds the
        # FORGED row of neighbor j = nbr[i, s] iff the adversary's edge
        # reaches this receiver, else j's clean row (identical for
        # benign j).  Peer rows may only be read here, through the
        # counted gather above (lint: topologydiscipline).
        def node_rows(nb, rrow):
            sel = jnp.take(rrow, nb)
            return jnp.where(sel[:, None], jnp.take(forged_pad, nb, axis=0),
                             jnp.take(clean_pad, nb, axis=0))

        with jax.named_scope("blades/select"):
            mat = jax.vmap(node_rows)(nbr_c, recv_c)  # (n_local, k1, d)

        degraded = None
        w_eff = w_c
        if fr.faults is not None:
            with jax.named_scope("blades/edge_faults"):
                # Symmetric edge dropout, pure in (fault_seed, round):
                # u_sym = min(u, u.T) keeps the realization symmetric
                # (a partitioned link is dead in both directions).
                round0 = srv.round[0]
                ek = jax.random.fold_in(fr.faults.round_key(round0),
                                        EDGE_FOLD)
                u = jax.random.uniform(ek, (n_real, n_real))
                drop_r = jnp.minimum(u, u.T) < fr.faults.dropout_rate_at(
                    round0)
                drop_full = jnp.zeros((n_pad, n_pad), bool)
                drop_full = drop_full.at[:n_real, :n_real].set(drop_r)
                drop_c = lax.dynamic_slice_in_dim(drop_full, start,
                                                  n_local, 0)
                dropped = jax.vmap(jnp.take)(drop_c, nbr_c)
                live = valid_c & (is_self | ~dropped)
                # Dead slots: zero mixing weight, own row in the matrix
                # (the static-shape analogue of a missing neighbor).
                w_eff = jnp.where(live, w_c, 0.0)
                own = lax.dynamic_slice_in_dim(clean_pad, start, n_local, 0)
                mat = jnp.where(live[:, :, None], mat, own[:, None, :])
                # Loud per-node degradation: live rows below the
                # aggregator's breakdown line a*f_i + b -> self-trust.
                mal_nbr = jax.vmap(jnp.take)(
                    jnp.broadcast_to(mal_pad, (n_local, n_pad)), nbr_c)
                f_i = (mal_nbr & live).sum(axis=1)
                degraded = live.sum(axis=1) < a_bd * f_i + b_bd

        # Gossip mixing in deviation form on the ROUND-INPUT params:
        # exact identity (up to +0.0) when all neighbor deviations are
        # bitwise zero — the complete-graph bit-identity mechanism.
        with jax.named_scope("blades/mix"):
            th_nbr = jax.vmap(lambda nb: jnp.take(theta, nb, axis=0))(nbr_c)
            mixed = th_local + jnp.einsum(
                "nk,nkd->nd", w_eff, th_nbr - th_local[:, None, :])

        # Per-node decomposed server step: robust aggregate over the
        # neighborhood matrix, optimizer step from the MIXED params.
        expects_trusted = getattr(fr.server.aggregator,
                                  "expects_trusted_row", False)
        k_agg1 = jax.random.fold_in(k_agg, 1)

        def node_agg(sv_i, mixed_i, mat_i):
            params_mixed = unravel(mixed_i)
            sv2 = ServerState(params=params_mixed, opt_state=sv_i.opt_state,
                              agg_state=sv_i.agg_state, round=sv_i.round)
            trusted = (fr.compute_trusted_update(params_mixed, k_agg1)
                       if expects_trusted else None)
            m2 = fr.server._with_trusted_row(mat_i, trusted)
            agg, ast = fr.server.aggregator(m2, sv2.agg_state, key=k_agg)
            return sv2, agg, ast

        with jax.named_scope("blades/aggregate"):
            sv2s, aggs, asts = jax.vmap(node_agg)(srv, mixed, mat)
        if degraded is not None:
            own_u = lax.dynamic_slice_in_dim(clean_pad, start, n_local, 0)
            aggs = jnp.where(degraded[:, None], own_u, aggs)

        def node_apply(sv_orig, sv2, agg, ast):
            new = fr.server.apply_aggregate(sv2, agg, ast)
            if fr.health_check:
                from blades_tpu.core.health import guard_server_state

                ok = jnp.isfinite(agg).all()
                # Fallback to the PRE-mix replica: a bad round leaves
                # the node exactly where it started, like dense.
                new = guard_server_state(ok, new, sv_orig)
            return new

        new_srv = jax.vmap(node_apply)(srv, sv2s, aggs, asts)

        aggn_local = jax.vmap(jnp.linalg.norm)(aggs)
        aggn = lax.all_gather(aggn_local, CLIENTS_AXIS, axis=0, tiled=True)
        rec.count_ici("aggnorm_gather", "all_gather", n_pad * 4, c)

        benign = (~malicious).astype(jnp.float32)
        losses_r = losses[:n_real]
        th_r = theta[:n_real]
        gram = th_r @ th_r.T
        sq = (jnp.diag(gram)[:, None] + jnp.diag(gram)[None, :] - 2.0 * gram)
        metrics = {
            "train_loss": (losses_r * benign).sum()
            / jnp.maximum(benign.sum(), 1.0),
            "update_norm_mean": jnp.linalg.norm(forged, axis=1).mean(),
            "agg_norm": aggn[0],
            "round": new_srv.round[0],
            "consensus_dist": jnp.sqrt(jnp.maximum(sq, 0.0).max()),
        }
        if degraded is not None:
            part_local = (degraded & (gidx < n_real)).sum().astype(jnp.int32)
            metrics["num_partitioned_nodes"] = lax.psum(part_local,
                                                        CLIENTS_AXIS)
            rec.count_ici("partitioned_psum", "psum", 4, c)
        else:
            metrics["num_partitioned_nodes"] = jnp.int32(0)
        if fr.health_check:
            metrics["num_unhealthy"] = (~healthy).sum()
            metrics["round_ok"] = jnp.isfinite(aggn[:n_real]).all()
        # Trace-time constant, the hier ici_bytes stamp pattern.
        metrics["gossip_ici_bytes"] = jnp.int32(rec.ici_bytes)
        new_state = RoundState(server=new_srv, client_opt=client_opt,
                               arrivals=getattr(state, "arrivals", None),
                               cohort=getattr(state, "cohort", None))
        return new_state, metrics

    return jax.jit(_step), rec


def gossip_federation(mesh: Mesh, round_state: RoundState, data_arrays):
    """Place a federation onto the mesh for the gossip path.

    Unlike :func:`~blades_tpu.parallel.mesh.shard_federation` (which
    REPLICATES the single server), the server state is STACKED to one
    replica per mesh-padded node (``n_pad = ceil(n / c) * c``) and
    sharded on the leading node axis alongside the client state and
    data — every chip owns a contiguous block of node replicas.  Ghost
    replicas train on empty shards and gossip with zero weight; the
    round program slices them away from every metric.
    """
    cs = client_axis_sharding(mesh)
    n_dev = mesh.shape[CLIENTS_AXIS]
    # Node count from the data (client_opt may be leafless, e.g. plain
    # SGD client optimizers).
    n = data_arrays[0].shape[0]
    n_pad = -(-n // n_dev) * n_dev
    server = jax.tree.map(
        lambda a: jax.device_put(
            jnp.broadcast_to(a[None], (n_pad,) + jnp.shape(a)), cs),
        round_state.server,
    )
    client_opt = jax.tree.map(
        lambda a: jax.device_put(pad_to_multiple(a, n_dev), cs),
        round_state.client_opt,
    )
    state = dataclasses.replace(round_state, server=server,
                                client_opt=client_opt)
    data = tuple(
        jax.device_put(pad_to_multiple(a, n_dev), cs) for a in data_arrays
    )
    return state, data


def reshard_gossip_state(mesh: Mesh, round_state: RoundState) -> RoundState:
    """Re-place a checkpointed gossip state (per-node server stack
    ALREADY in the leading axis) onto the mesh — the resume half of
    :func:`gossip_federation`."""
    cs = client_axis_sharding(mesh)
    return dataclasses.replace(
        round_state,
        server=jax.device_put(round_state.server, cs),
        client_opt=jax.device_put(round_state.client_opt, cs),
    )


def gossip_evaluate(fr: FedRound) -> Callable:
    """Evaluation for gossip states: score the node-0 head replica with
    the standard dense evaluation — on a healthy (un-partitioned) run
    consensus makes every head equivalent, and ``consensus_dist`` is the
    sensor that says when that assumption broke."""

    @jax.jit
    def _evaluate(state: RoundState, test_x, test_y, lengths):
        head = jax.tree.map(lambda a: a[0], state.server)
        st = dataclasses.replace(state, server=head)
        return fr.evaluate(st, test_x, test_y, lengths)

    return _evaluate
