"""Decentralized gossip federation: peer graphs + serverless rounds.

The subsystem behind ``execution="gossip"`` (ROADMAP item 4, the
decentralized half of BLADE-FL arXiv:2012.02044): static peer graphs
with doubly-stochastic mixing (:mod:`blades_tpu.topology.graph`) and the
per-node robust gossip round (:mod:`blades_tpu.topology.gossip`).
"""

from blades_tpu.topology.graph import (  # noqa: F401
    GRAPHS,
    MIXINGS,
    NeighborTables,
    TopologyConfig,
    get_topology,
)
from blades_tpu.topology.gossip import (  # noqa: F401
    EDGE_FOLD,
    gossip_evaluate,
    gossip_federation,
    gossip_step,
    reshard_gossip_state,
)
