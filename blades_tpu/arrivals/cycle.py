"""The buffered-async aggregation cycle as ONE pure jittable program.

Where the synchronous round (:mod:`blades_tpu.core.round`) runs every
client lockstep against the same params, the async cycle consumes ``K``
buffered ARRIVAL EVENTS — ``(client, tick, version)`` triples the host
engine accumulated — and for each event computes that client's local
round against the global params VERSION it last pulled, read from the
params-history ring the chaos layer's stale-update ring buffer was
promoted into: rather than replaying stale *updates* (the straggler
fault model), the ring retains stale *params* ``(H+1, d)`` and the
cycle computes honest updates against them — the FedBuff semantics.

    gather event clients' shards + opt states
    -> vmap(local_round at per-event params version) over the K events
    -> chaos lane corruption (event realization)
    -> adversary forge (lazy/free-riders included)
    -> staleness-weighted robust aggregate (Server.step_buffered)
    -> server step, params pushed into the history ring

PRNG discipline: each event's training key is
``fold_in(fold_in(key_base, tick), client)`` — pure in ``(seed, tick,
client)``, so a resumed trial re-derives the identical stream from the
checkpointed tick alone, with no key chain to replay.  The aggregation
key folds the server version.  Arrival/fault realizations never touch
these streams (they fold their own seeds, host-side).
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from blades_tpu.core.round import RoundState
from blades_tpu.data.sampler import sample_batch
from blades_tpu.utils.tree import ravel_fn

#: Fold separating the async per-event training stream from the sync
#: driver's split chain of ``PRNGKey(seed)``.
ASYNC_TRAIN_FOLD = 0xA51C
#: Fold deriving the per-cycle aggregation key from the same base.
ASYNC_AGG_FOLD = 0xA99E


def event_train_key(key_base: jax.Array, tick, client) -> jax.Array:
    """The training key for one arrival event: pure in
    ``(seed, tick, client)``."""
    return jax.random.fold_in(jax.random.fold_in(key_base, tick), client)


def cycle_agg_key(key_base: jax.Array, version) -> jax.Array:
    """The aggregation key for the cycle fired at server ``version``."""
    return jax.random.fold_in(
        jax.random.fold_in(key_base, ASYNC_AGG_FOLD), version)


def init_history(params, staleness_cap: int) -> jax.Array:
    """The ``(H+1, d)`` params-history ring, every row the init params
    (a client pulling before the first aggregation sees version 0)."""
    ravel, _, d = ravel_fn(params)
    vec = ravel(params)
    return jnp.tile(vec[None, :], (staleness_cap + 1, 1))


def build_cycle(fed_round, *, staleness_cap: int, weight_schedule: str,
                weight_power: float, weight_cutoff: int,
                corrupt_mode=None, windowed_state: bool = False,
                forensics: bool = False):
    """Build the pure cycle function for ``fed_round`` (jit the result).

    Returns ``cycle(state, data_x, data_y, lengths, ev_clients,
    ev_ticks, ev_stale, ev_malicious, ev_corrupt, key_base, k_agg) ->
    (new_state, metrics)`` where the ``ev_*`` arrays are the host
    engine's ``(K,)`` event columns.  ``state.arrivals`` must carry the
    ``(H+1, d)`` params-history ring (:func:`init_history`).

    ``windowed_state=True`` is the out-of-core composition
    (blades_tpu/state): the registered population's opt rows live in a
    host/disk :class:`~blades_tpu.state.store.ClientStateStore`, so
    the cycle receives the EVENT COHORT's rows directly —
    ``state.client_opt`` is the ``(K, ...)`` gathered stack and
    ``data_x``/``data_y``/``lengths`` are the ``(K, ...)`` event
    shards the engine gathered host-side — and returns the updated
    cohort stack for the engine to scatter back, instead of indexing/
    updating a full ``(n, ...)`` device stack in the traced program.
    The gathered rows are bit-equal to what the resident indexing
    reads, so both modes produce identical cycles.

    ``forensics=True`` runs the aggregator's per-lane diagnostics on
    the staleness-scaled event matrix (``Server.step_buffered_diag``)
    and emits the cohort-shaped forensics bundle: the ``lane_*`` arrays
    are indexed IN EVENT ORDER, so lane ``i`` diagnoses registered
    client ``ev_clients[i]`` — the host driver stamps that id-vector
    alongside as ``lane_forensics["clients"]``.  Detection P/R/FPR are
    scored against the events' own malicious mask (every buffered row
    was delivered, so no participation conditioning applies).
    """
    task = fed_round.task
    hooks = fed_round._hooks()
    adv = fed_round.adversary
    # Lazy "replay" free-riders: malicious events compute against the
    # OLDEST retained params regardless of their true pull — they ship
    # maximally stale work while claiming freshness (the attack only an
    # async server can express; see adversaries.LazyAdversary).
    stale_replay = bool(getattr(adv, "wants_stale_replay", False))
    # Campaign adversaries (adversaries/campaigns.py): attacks that
    # adapt over virtual time declare `wants_ticks` and receive the
    # per-event arrival ticks — the same deterministic columns the
    # engine already built, so scheduled attacks replay bit-identically.
    wants_ticks = bool(getattr(adv, "wants_ticks", False))
    fill_value = None
    if corrupt_mode is not None:
        from blades_tpu.faults.injector import _CORRUPT_FILL

        fill_value = _CORRUPT_FILL[corrupt_mode]
    batch_size = fed_round.batch_size
    num_batches = fed_round.num_batches_per_round

    def cycle(
        state: RoundState,
        data_x: jax.Array,
        data_y: jax.Array,
        lengths: jax.Array,
        ev_clients: jax.Array,
        ev_ticks: jax.Array,
        ev_stale: jax.Array,
        ev_malicious: jax.Array,
        ev_corrupt: jax.Array,
        key_base: jax.Array,
        k_agg: jax.Array,
    ) -> Tuple[RoundState, dict]:
        hist = state.arrivals  # (H+1, d); row j = params j versions ago
        _, unravel, _ = ravel_fn(state.server.params)
        with jax.named_scope("blades/arrivals"):
            idx = jnp.clip(ev_stale, 0, staleness_cap)
            if stale_replay:
                idx = jnp.where(ev_malicious, staleness_cap, idx)
            params_vecs = hist[idx]  # (K, d) per-event params versions

        if windowed_state:
            ex, ey, eln = data_x, data_y, lengths
            opt_sel = state.client_opt
        else:
            ex = data_x[ev_clients]
            ey = data_y[ev_clients]
            eln = lengths[ev_clients]
            opt_sel = jax.tree.map(lambda a: a[ev_clients],
                                   state.client_opt)

        def one_event(pvec, opt, cx, cy, ln, tick, client, mal):
            ek = event_train_key(key_base, tick, client)
            k_sample, k_train = jax.random.split(ek)
            bkeys = jax.random.split(k_sample, num_batches)
            bx, by = jax.vmap(
                lambda kb: sample_batch(kb, cx, cy, ln, batch_size)
            )(bkeys)
            return task.local_round(
                unravel(pvec), opt, bx, by, k_train, mal,
                hooks.data, hooks.grad, hooks.round_begin, hooks.round_end,
            )

        with jax.named_scope("blades/step"):
            updates, new_opt, losses = jax.vmap(one_event)(
                params_vecs, opt_sel, ex, ey, eln,
                ev_ticks, ev_clients, ev_malicious,
            )
        if fill_value is not None:
            # Chaos lane corruption at delivery: the event realization is
            # host-computed (pure in (fault_seed, tick, client)); here the
            # flagged rows are overwritten with the configured garbage.
            with jax.named_scope("blades/faults"):
                updates = jnp.where(
                    ev_corrupt[:, None], jnp.full_like(updates, fill_value),
                    updates)
        if adv is not None and hasattr(adv, "on_updates_ready"):
            k_adv = jax.random.fold_in(k_agg, 2)
            forge_kwargs = {}
            if wants_ticks:
                forge_kwargs["ticks"] = ev_ticks
            with jax.named_scope("blades/forge"):
                updates = adv.on_updates_ready(
                    updates, ev_malicious, k_adv,
                    aggregator=fed_round.server.aggregator,
                    global_params=state.server.params,
                    **forge_kwargs,
                )
        trusted_update = fed_round.compute_trusted_update(
            state.server.params, jax.random.fold_in(k_agg, 1))
        if forensics:
            # Non-destructive lane-health probe at the same pre-aggregate
            # point the sync round takes it (post-corruption, post-forge:
            # what the server is about to judge).
            healthy = jnp.isfinite(updates).all(axis=-1)
        diag = None
        with jax.named_scope("blades/aggregate"):
            if forensics:
                server, agg, diag = fed_round.server.step_buffered_diag(
                    state.server, updates, staleness=ev_stale, key=k_agg,
                    trusted_update=trusted_update, schedule=weight_schedule,
                    power=weight_power, cutoff=weight_cutoff,
                )
            else:
                server, agg = fed_round.server.step_buffered(
                    state.server, updates, staleness=ev_stale, key=k_agg,
                    trusted_update=trusted_update, schedule=weight_schedule,
                    power=weight_power, cutoff=weight_cutoff,
                )
        ravel, _, _ = ravel_fn(server.params)
        hist = jnp.concatenate([ravel(server.params)[None], hist[:-1]],
                               axis=0)
        if windowed_state:
            client_opt = new_opt  # (K, ...): the engine scatters it back
        else:
            client_opt = jax.tree.map(
                lambda full, upd: full.at[ev_clients].set(upd),
                state.client_opt, new_opt,
            )
        benign = ((~ev_malicious) & (~ev_corrupt)).astype(jnp.float32)
        train_loss = (losses * benign).sum() / jnp.maximum(benign.sum(), 1.0)
        metrics = {
            "train_loss": train_loss,
            # Norms of the delivered rows (pre-weighting: the discount is
            # aggregation geometry, not client behavior).
            "update_norm_mean": jnp.linalg.norm(updates, axis=1).mean(),
            "agg_norm": jnp.linalg.norm(agg),
            "round": server.round,
        }
        if forensics:
            from blades_tpu.obs.forensics import detection_metrics

            # Cohort-shaped forensics: lane i diagnoses registered
            # client ev_clients[i].  Same "lane_" bundle contract as the
            # sync round (f32 for uniform scan stacking); the driver
            # pairs it with the event id-vector.
            metrics.update(detection_metrics(diag["benign_mask"],
                                             ev_malicious))
            metrics["num_unhealthy"] = (~healthy).sum()
            metrics["lane_benign_mask"] = diag["benign_mask"].astype(
                jnp.float32)
            metrics["lane_scores"] = diag["scores"].astype(jnp.float32)
            metrics["lane_healthy"] = healthy.astype(jnp.float32)
            metrics["lane_update_norms"] = jnp.linalg.norm(
                updates, axis=1).astype(jnp.float32)
        return RoundState(
            server=server, client_opt=client_opt,
            stale=getattr(state, "stale", None),
            residual=getattr(state, "residual", None),
            arrivals=hist,
            cohort=getattr(state, "cohort", None),
        ), metrics

    return cycle
