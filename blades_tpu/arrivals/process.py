"""Deterministic Poisson arrival process: realizations pure in (seed, tick).

Real clients arrive on their own clocks.  The simulator's clock is a
VIRTUAL integer tick (no wall-clock read anywhere in this package's
realization path — the trace-discipline lint fixture pair pins that), and
the arrival process is the discrete-time Poisson process: at each tick
every client independently arrives with Bernoulli probability ``rate``,
so inter-arrival times are geometric — the discrete-time analogue of the
exponential inter-arrival times of a continuous Poisson process, with
mean ``1 / rate`` ticks between one client's deliveries.

Determinism contract (the chaos layer's, verbatim): the arrival PRNG
stream is ``fold_in(fold_in(PRNGKey(seed), _ARRIVAL_STREAM), tick)`` —
pure in ``(seed, tick)``, independent of the training key — so the SAME
arrival realization replays across retries, resumes, and execution
modes.  A trial killed mid-stream and restored from a checkpoint
re-experiences the identical traffic.

Heterogeneous clocks: ``slow_fraction``/``slow_factor`` mark the LAST
``floor(slow_fraction * n)`` client lanes as slow devices arriving at
``rate * slow_factor`` (the static suffix mirrors the malicious-PREFIX
convention of :func:`~blades_tpu.adversaries.make_malicious_mask`, so
the two sets only overlap when both cover most of the federation) —
slow clients deliver against older model versions, widening the
staleness spectrum the weight schedules discount.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

#: Fold separating the arrival stream from the chaos layer's fault stream
#: (``FaultInjector.round_key`` folds the bare ``PRNGKey(seed)``) when the
#: two processes share a seed.
_ARRIVAL_STREAM = 0x0A51


@dataclasses.dataclass(frozen=True)
class ArrivalProcess:
    """Static arrival config; realizations are pure in ``(seed, tick)``.

    Attributes:
        seed: arrival-process seed, independent of the training key.
        rate: per-client per-tick Bernoulli arrival probability (the
            discrete-time Poisson intensity).
        rate_schedule: optional ``((tick, rate), ...)`` piecewise-constant
            override — from each listed tick on, arrivals run at that
            rate (``rate`` applies before the first entry).  Models
            diurnal traffic and flash crowds.
        slow_fraction: fraction of clients (a static lane SUFFIX) whose
            arrival rate is multiplied by ``slow_factor``.
        slow_factor: rate multiplier for the slow cohort.
    """

    seed: int = 0
    rate: float = 0.25
    rate_schedule: Optional[Tuple[Tuple[int, float], ...]] = None
    slow_fraction: float = 0.0
    slow_factor: float = 0.25

    def __post_init__(self):
        if not 0.0 < self.rate <= 1.0:
            raise ValueError(
                f"rate must be in (0, 1], got {self.rate} (0 would mean "
                "no client ever arrives)"
            )
        if not 0.0 <= self.slow_fraction <= 1.0:
            raise ValueError(
                f"slow_fraction must be in [0, 1], got {self.slow_fraction}"
            )
        if not 0.0 < self.slow_factor <= 1.0:
            raise ValueError(
                f"slow_factor must be in (0, 1], got {self.slow_factor} "
                "(slow clients arrive less often, never more)"
            )
        if self.rate_schedule is not None:
            # Normalize to a sorted tuple of (int, float) tuples: the
            # process is static jit config and must stay hashable.
            sched = tuple(sorted(
                (int(t), float(v)) for t, v in self.rate_schedule))
            for t, v in sched:
                if t < 0 or not 0.0 < v <= 1.0:
                    raise ValueError(
                        f"rate_schedule entries must be (tick >= 0, rate "
                        f"in (0, 1]), got ({t}, {v})"
                    )
            object.__setattr__(self, "rate_schedule", sched)

    # -- realizations --------------------------------------------------------

    def base_key(self) -> jax.Array:
        """The arrival stream's root key — seed only, training key never."""
        return jax.random.fold_in(
            jax.random.PRNGKey(self.seed), _ARRIVAL_STREAM)

    def tick_key(self, tick) -> jax.Array:
        """The arrival PRNG key for one virtual tick: pure in
        ``(seed, tick)``."""
        return jax.random.fold_in(self.base_key(), tick)

    def rate_at(self, tick) -> jax.Array:
        """Piecewise-constant arrival rate at ``tick`` (traced-safe)."""
        if not self.rate_schedule:
            return jnp.float32(self.rate)
        bounds = jnp.asarray([t for t, _ in self.rate_schedule], jnp.int32)
        rates = jnp.asarray(
            [self.rate] + [v for _, v in self.rate_schedule], jnp.float32)
        return rates[jnp.searchsorted(bounds, tick, side="right")]

    def client_rates(self, tick, num_clients: int) -> jax.Array:
        """Per-lane arrival rates at ``tick``: the base rate with the
        slow-suffix multiplier applied."""
        r = self.rate_at(tick)
        rates = jnp.full((num_clients,), r, jnp.float32)
        num_slow = int(self.slow_fraction * num_clients)
        if num_slow:
            slow = jnp.arange(num_clients) >= num_clients - num_slow
            rates = jnp.where(slow, r * jnp.float32(self.slow_factor), rates)
        return rates

    def arrivals_at(self, tick, num_clients: int) -> jax.Array:
        """One tick's arrival realization: ``(n,)`` bool, client ``i``
        delivered an update at ``tick``.  Pure in ``(seed, tick)``."""
        u = jax.random.uniform(self.tick_key(tick), (num_clients,))
        return u < self.client_rates(tick, num_clients)

    def arrivals_window(self, tick0: int, num_ticks: int,
                        num_clients: int) -> jax.Array:
        """``(num_ticks, num_clients)`` bool — ticks ``tick0 ..
        tick0 + num_ticks - 1`` realized at once (bit-identical to
        per-tick :meth:`arrivals_at` calls; the host engine consumes
        windows to amortize realization dispatches)."""
        ticks = tick0 + jnp.arange(num_ticks)
        return jax.vmap(lambda t: self.arrivals_at(t, num_clients))(ticks)
