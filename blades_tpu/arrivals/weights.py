"""Staleness weight schedules for buffered-async aggregation.

A buffered row's staleness ``k = server_version - version the update was
computed against`` (0 = computed against the current model).  Every
schedule maps ``(K,)`` integer staleness to ``(K,)`` f32 weights; rows
are then scaled by the MEAN-normalized weight
(:func:`normalized_row_scale`) before the robust aggregator runs, so:

- **Mean** returns exactly the staleness-weighted average
  ``sum(w_i u_i) / sum(w_i)`` (the FedBuff fixed point);
- every row-geometry defense (Median, Trimmedmean, Multikrum, GeoMed,
  ...) sees stale rows geometrically discounted toward the origin — the
  standard staleness-aware robustification (ByzFL frames this as the
  open hard case; the discount is the conservative baseline).

Schedules:

==============  ==========================================================
``constant``    ``w(k) = 1`` — staleness ignored (the ablation baseline)
``polynomial``  ``w(k) = (1 + k)^-power`` — FedBuff's ``1/sqrt(1+k)`` at
                the default ``power = 0.5``
``inverse``     ``w(k) = 1 / (1 + k)``
``cutoff``      ``w(k) = 1 if k <= cutoff else 0`` — hard staleness bound
==============  ==========================================================
"""

from __future__ import annotations

import jax.numpy as jnp

STALENESS_SCHEDULES = ("constant", "polynomial", "inverse", "cutoff")


def staleness_weights(schedule: str, staleness, *, power: float = 0.5,
                      cutoff: int = 16):
    """``(K,)`` staleness ints -> ``(K,)`` f32 weights (pure, jittable;
    ``schedule`` is static config)."""
    k = jnp.asarray(staleness).astype(jnp.float32)
    if schedule == "constant":
        return jnp.ones_like(k)
    if schedule == "polynomial":
        return (1.0 + k) ** jnp.float32(-power)
    if schedule == "inverse":
        return 1.0 / (1.0 + k)
    if schedule == "cutoff":
        return (k <= jnp.float32(cutoff)).astype(jnp.float32)
    raise ValueError(
        f"unknown staleness weight schedule {schedule!r}; known: "
        f"{STALENESS_SCHEDULES}"
    )


def normalized_row_scale(weights):
    """Mean-normalized per-row scale ``w_i / mean(w)``: feeding
    ``u_i * scale_i`` to a plain Mean yields exactly the weighted average
    ``sum(w u) / sum(w)``, and an all-equal weight vector degenerates to
    the identity (no schedule => bit-identical rows).

    An ALL-ZERO weight vector (a ``cutoff`` cycle whose every row is
    over-stale) scales every row to zero: the batch is discarded and the
    server takes a zero step — the schedule's contract, surfaced loudly
    by the host engine (``AsyncEngine.run_cycle`` warns) since a traced
    program cannot."""
    w = jnp.asarray(weights)
    return w / jnp.maximum(w.mean(), 1e-12)
