"""AsyncEngine: the host driver of the buffered-async execution mode.

The engine owns everything the cycle program (:mod:`.cycle`) must not
trace: the virtual tick clock, the version vector (which global model
version each client last pulled), the bounded arrival buffer, and the
chaos-layer realization of dropout/corruption over arrivals.  All of it
is deterministic host metadata — ints and short lists — checkpointed via
:meth:`host_state` next to the pickled :class:`RoundState` and restored
bit-identically.

One :meth:`run_cycle` call is one server round:

1. advance the virtual clock, realizing arrivals (pure in
   ``(arrival_seed, tick)``) and the chaos layer's dropout/corruption
   (pure in ``(fault_seed, tick)``) window-at-a-time, pushing surviving
   arrivals into the bounded buffer (full buffer => overflow drop) and
   advancing each arriving client's pulled version;
2. once the buffer holds ``agg_every`` unique-client events, pop them
   (FIFO) and fire ONE cycle dispatch: per-event local rounds against
   the params versions the clients pulled, chaos corruption, adversary
   forge, staleness-weighted robust aggregation, server step;
3. report the host-side ingest digest (tick, staleness stats, buffer
   occupancy, drop/overflow counters) for the metrics row.

No wall clock is read here: time is the virtual tick, and the ingest
*rate* (``updates_per_sec``) is measured by the driver through the span
layer's sanctioned clock (:func:`blades_tpu.obs.trace.now`).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from blades_tpu.arrivals.buffer import ArrivalEvent, UpdateBuffer
from blades_tpu.arrivals.cycle import (
    ASYNC_TRAIN_FOLD,
    build_cycle,
    cycle_agg_key,
    init_history,
)
from blades_tpu.arrivals.process import ArrivalProcess
from blades_tpu.arrivals.weights import STALENESS_SCHEDULES

#: Ticks realized per host dispatch while filling the buffer.
_REALIZE_WINDOW = 64


@dataclasses.dataclass(frozen=True)
class AsyncSpec:
    """Static buffered-async config (``FedavgConfig.async_config``).

    Attributes:
        seed: arrival-process seed (defaults to the trial seed via
            ``FedavgConfig.get_async_spec``); independent of the
            training key.
        rate / rate_schedule / slow_fraction / slow_factor: the
            :class:`~blades_tpu.arrivals.process.ArrivalProcess` knobs.
        agg_every: K — the server fires a robust aggregation every K
            buffered arrivals (the FedBuff buffer size).
        buffer_capacity: bounded-buffer capacity B >= K; arrivals past a
            full buffer are dropped (``buffer_overflow``).  0 = ``2*K``.
        staleness_cap: H — params-history depth; an update older than H
            versions is computed against the oldest retained params
            (true staleness still reported and weighted).
        weight_schedule / weight_power / weight_cutoff: the staleness
            discount (:mod:`blades_tpu.arrivals.weights`).
        max_ticks_per_cycle: starvation guard — a cycle that cannot
            collect K unique-client arrivals within this many ticks
            raises instead of spinning forever.
        ticks_per_sec: wall-clock calibration of the virtual tick
            (``0.0`` = uncalibrated, the default).  PURELY a sizing /
            reporting aid — it never enters the realization path
            (``tick_key`` folds ``(seed, tick)`` and nothing else), so
            two specs differing only here realize bit-identical
            traffic.  :func:`size_for_target` consumes it to derive
            ``agg_every``/``buffer_capacity`` from an
            ``updates_per_sec`` target.
    """

    seed: int = 0
    rate: float = 0.25
    rate_schedule: Optional[Tuple[Tuple[int, float], ...]] = None
    slow_fraction: float = 0.0
    slow_factor: float = 0.25
    agg_every: int = 8
    buffer_capacity: int = 0
    staleness_cap: int = 8
    weight_schedule: str = "polynomial"
    weight_power: float = 0.5
    weight_cutoff: int = 16
    max_ticks_per_cycle: int = 100_000
    ticks_per_sec: float = 0.0

    def __post_init__(self):
        if self.agg_every < 1:
            raise ValueError(f"agg_every must be >= 1, got {self.agg_every}")
        if self.ticks_per_sec < 0:
            raise ValueError(
                f"ticks_per_sec must be >= 0 (0 = uncalibrated), got "
                f"{self.ticks_per_sec}")
        if self.buffer_capacity and self.buffer_capacity < self.agg_every:
            raise ValueError(
                f"buffer_capacity={self.buffer_capacity} < agg_every="
                f"{self.agg_every}: the buffer could never hold one "
                "aggregation batch")
        if self.staleness_cap < 1:
            raise ValueError(
                f"staleness_cap must be >= 1, got {self.staleness_cap}")
        if self.weight_schedule not in STALENESS_SCHEDULES:
            raise ValueError(
                f"weight_schedule must be one of {STALENESS_SCHEDULES}, "
                f"got {self.weight_schedule!r}")
        if self.weight_power <= 0:
            raise ValueError(
                f"weight_power must be > 0, got {self.weight_power}")
        if self.weight_cutoff < 0:
            raise ValueError(
                f"weight_cutoff must be >= 0, got {self.weight_cutoff}")
        if self.max_ticks_per_cycle < 1:
            raise ValueError("max_ticks_per_cycle must be >= 1")
        # Range checks of the process knobs fail fast here too.
        self.process()

    @property
    def effective_capacity(self) -> int:
        return self.buffer_capacity or 2 * self.agg_every

    def process(self) -> ArrivalProcess:
        return ArrivalProcess(
            seed=self.seed, rate=self.rate,
            rate_schedule=self.rate_schedule,
            slow_fraction=self.slow_fraction,
            slow_factor=self.slow_factor,
        )


def expected_arrivals_per_sec(spec: AsyncSpec, num_clients: int) -> float:
    """Expected wall-clock arrival supply of a CALIBRATED spec
    (``ticks_per_sec > 0``): the per-tick Bernoulli mass over the
    fast/slow lane split, scaled by the tick rate.  The schedule-free
    base rate is used — a ``rate_schedule`` makes supply time-varying
    and sizing should target the base regime."""
    if spec.ticks_per_sec <= 0:
        raise ValueError(
            "expected_arrivals_per_sec needs a calibrated spec: set "
            "ticks_per_sec > 0")
    n_slow = int(spec.slow_fraction * num_clients)
    n_fast = num_clients - n_slow
    per_tick = n_fast * spec.rate + n_slow * spec.rate * spec.slow_factor
    return float(per_tick * spec.ticks_per_sec)


def size_for_target(spec: AsyncSpec, num_clients: int,
                    target_updates_per_sec: float, *,
                    agg_interval_sec: float = 1.0) -> AsyncSpec:
    """Derive ``agg_every``/``buffer_capacity`` from a wall-clock
    ``updates_per_sec`` target (ROADMAP item 5's calibrated-ticks
    residual): size the aggregation batch so one cycle ingests about
    ``agg_interval_sec`` worth of the targeted traffic, with the usual
    ``2*K`` bounded buffer behind it.  Raises when the target exceeds
    the spec's expected arrival supply — an operator asking for more
    throughput than the fleet delivers must hear it at config time,
    not starve at tick time.  Returns a new spec; the arrival
    realization knobs (seed/rate/schedule) are untouched, so the
    resized spec replays the identical traffic."""
    supply = expected_arrivals_per_sec(spec, num_clients)
    if target_updates_per_sec <= 0:
        raise ValueError(
            f"target_updates_per_sec must be > 0, got "
            f"{target_updates_per_sec}")
    if agg_interval_sec <= 0:
        raise ValueError(
            f"agg_interval_sec must be > 0, got {agg_interval_sec}")
    if target_updates_per_sec > supply:
        raise ValueError(
            f"target_updates_per_sec={target_updates_per_sec:g} exceeds "
            f"the spec's expected arrival supply {supply:g}/s "
            f"(rate={spec.rate}, ticks_per_sec={spec.ticks_per_sec}, "
            f"{num_clients} clients) — raise the rate/fleet or lower "
            "the target")
    agg_every = int(np.clip(
        round(target_updates_per_sec * agg_interval_sec), 1, num_clients))
    return dataclasses.replace(
        spec, agg_every=agg_every, buffer_capacity=2 * agg_every)


class AsyncEngine:
    """Host driver pairing an :class:`AsyncSpec` with a ``FedRound``."""

    def __init__(self, fed_round, spec: AsyncSpec, num_clients: int, *,
                 train_seed: int, fault_injector=None, state_store=None,
                 data_store=None, forensics: bool = False):
        if spec.agg_every > num_clients:
            raise ValueError(
                f"agg_every={spec.agg_every} > num_clients={num_clients}: "
                "a cycle aggregates at most one event per client")
        if fault_injector is not None and fault_injector.num_stragglers:
            raise ValueError(
                "the async arrival model subsumes the straggler fault "
                "process (staleness is first-class); configure "
                "num_stragglers=0 under execution='async'")
        self.fed_round = fed_round
        self.spec = spec
        self.num_clients = int(num_clients)
        self.process = spec.process()
        self.faults = fault_injector
        # Out-of-core composition (blades_tpu/state): the registered
        # population's opt rows live behind a host/disk store — keyed,
        # like the version vector below, by REGISTERED id — and each
        # cycle gathers/scatters only the event cohort's rows (the
        # cycle program then carries (K, ...) cohort-windowed buffers
        # instead of the full (n, ...) stack).
        self.state_store = state_store
        # Out-of-core data plane (blades_tpu/data): a DataPrefetcher
        # over the training-shard store — the event cohort's data rows
        # are gathered per cycle instead of indexing resident host
        # stacks.  None = legacy host-array staging (bit-identical by
        # the store contract either way).
        self.data_store = data_store
        from blades_tpu.state.store import StoreStats

        self.store_stats = StoreStats()
        corrupt_mode = (fault_injector.corrupt_mode
                        if fault_injector is not None
                        and fault_injector.corrupt_rate > 0.0 else None)
        self._corrupt_mode = corrupt_mode
        self._forensics = bool(forensics)
        # Live actuator values (the control plane's hooks below).  They
        # start at the spec's statics and only the controller moves them
        # — spec stays frozen provenance, these are the running truth,
        # checkpointed via host_state so a resume re-applies them.
        self.agg_every = int(spec.agg_every)
        self.weight_cutoff = int(spec.weight_cutoff)
        self.quarantine: frozenset = frozenset()
        self.arrivals_quarantined = 0
        self._build_cycle()
        # Per-event training keys fold (seed, tick, client) off this base
        # — the async analogue of the sync driver's split chain, with no
        # chain state to checkpoint.
        self._key_base = jax.random.fold_in(
            jax.random.PRNGKey(int(train_seed)), ASYNC_TRAIN_FOLD)
        self._realize = jax.jit(self._realize_window)

        # -- deterministic host state (checkpointed via host_state) ----------
        self.tick = 0                      # next virtual tick to realize
        self.version = 0                   # global model version
        self.client_versions = np.zeros(self.num_clients, np.int64)
        self.buffer = UpdateBuffer(spec.effective_capacity)
        self.arrivals_total = 0
        self.arrivals_dropped = 0          # chaos dropout (never buffered)
        self.buffer_overflow = 0           # full-buffer drops
        self.last_info: Dict[str, Any] = {}
        # The LAST cycle's event cohort, host-side — the id-vector
        # cohort-shaped forensics lanes are indexed by (lane i of the
        # diag arrays is registered client last_clients[i]) and the
        # per-event staleness the client ledger folds in.  Derived from
        # the same deterministic event columns run_cycle already builds,
        # so they replay identically across kill-and-resume.
        self.last_clients: Any = None      # (K,) np.int32 registered ids
        self.last_staleness: Any = None    # (K,) np.int32 staleness

    def _build_cycle(self) -> None:
        """(Re)jit the cycle program against the LIVE weight_cutoff —
        the one actuator build_cycle closure-captures, so a controller
        move on it rebuilds the dispatch (a new jit cache entry; the
        agg_every shape change retraces within the same wrapper)."""
        self._cycle = jax.jit(build_cycle(
            self.fed_round, staleness_cap=self.spec.staleness_cap,
            weight_schedule=self.spec.weight_schedule,
            weight_power=self.spec.weight_power,
            weight_cutoff=self.weight_cutoff,
            corrupt_mode=self._corrupt_mode,
            windowed_state=self.state_store is not None,
            forensics=self._forensics,
        ))

    # -- control-plane actuator hooks ----------------------------------------
    # All four are host-side and deterministic: they touch only host
    # metadata (plus one re-jit), never a traced value mid-flight, and
    # every applied value rides host_state so kill-and-resume replays
    # the controlled trajectory bit-identically.

    def set_agg_every(self, k: int) -> None:
        """Shrink/adjust the aggregation cadence K (cycle fires every K
        unique-client buffered events)."""
        k = int(k)
        if not (1 <= k <= self.num_clients):
            raise ValueError(
                f"agg_every must be in [1, {self.num_clients}], got {k}")
        if self.buffer.capacity < k:
            raise ValueError(
                f"agg_every={k} exceeds buffer capacity "
                f"{self.buffer.capacity}")
        self.agg_every = k

    def set_buffer_capacity(self, capacity: int) -> None:
        """Grow the bounded arrival buffer, carrying pending events
        over (the control plane only grows it, so the restore always
        fits)."""
        capacity = int(capacity)
        if capacity < max(self.agg_every, self.buffer.fill):
            raise ValueError(
                f"buffer capacity {capacity} < max(agg_every="
                f"{self.agg_every}, pending fill {self.buffer.fill})")
        pending = self.buffer.state()
        self.buffer = UpdateBuffer(capacity)
        self.buffer.restore(pending)

    def set_weight_cutoff(self, cutoff: int) -> None:
        """Relax (or tighten) the staleness weight cutoff — rebuilds the
        cycle dispatch (the cutoff is closure-captured static)."""
        cutoff = int(cutoff)
        if cutoff < 0:
            raise ValueError(f"weight_cutoff must be >= 0, got {cutoff}")
        if cutoff == self.weight_cutoff:
            return
        self.weight_cutoff = cutoff
        self._build_cycle()

    def set_quarantine(self, clients) -> None:
        """Mask a client set out of aggregation at INGEST: their
        arrivals are counted (``arrivals_quarantined``) and advance
        their pulled version — the client keeps working, the server
        discards the delivery — but are never buffered.  Zero re-jit,
        pure host filtering."""
        q = frozenset(int(c) for c in clients)
        bad = sorted(c for c in q if not (0 <= c < self.num_clients))
        if bad:
            raise ValueError(f"quarantine ids out of range: {bad}")
        if self.num_clients - len(q) < self.agg_every:
            raise ValueError(
                f"quarantining {len(q)}/{self.num_clients} clients "
                f"leaves fewer than agg_every={self.agg_every} eligible "
                "— the cycle could never fill")
        self.quarantine = q

    # -- realization ---------------------------------------------------------

    def _realize_window(self, tick0):
        """``(W, n)`` arrival / dropout / corruption realizations for
        ticks ``tick0 .. tick0+W-1`` — each pure in its own
        ``(seed, tick)`` stream (jitted once; W is static)."""
        n = self.num_clients
        arrivals = self.process.arrivals_window(tick0, _REALIZE_WINDOW, n)
        if self.faults is None:
            flat = jnp.zeros((_REALIZE_WINDOW, n), bool)
            return arrivals, flat, flat

        def one_tick(t):
            # The sync injector's key discipline, per TICK instead of per
            # round: realizations replay identically across resumes.
            k_drop, _k_strag, k_corr = jax.random.split(
                self.faults.round_key(t), 3)
            drop = (jax.random.uniform(k_drop, (n,))
                    < self.faults.dropout_rate_at(t))
            corrupt = (jax.random.uniform(k_corr, (n,))
                       < self.faults.corrupt_rate)
            return drop, corrupt

        ticks = tick0 + jnp.arange(_REALIZE_WINDOW)
        drops, corrupts = jax.vmap(one_tick)(ticks)
        return arrivals, drops, corrupts

    def advance_until_ready(self) -> None:
        """Advance the virtual clock until the buffer holds one
        aggregation batch (``agg_every`` unique-client events)."""
        k = self.agg_every
        start = self.tick
        while self.buffer.unique_clients() < k:
            if self.tick - start > self.spec.max_ticks_per_cycle:
                raise RuntimeError(
                    f"arrival starvation: {self.tick - start} ticks "
                    f"without {k} unique-client arrivals (rate="
                    f"{self.spec.rate}, buffer capacity "
                    f"{self.buffer.capacity}) — raise the rate or shrink "
                    "agg_every/buffer pressure")
            arrivals, drops, corrupts = jax.device_get(
                self._realize(self.tick))
            for w in range(_REALIZE_WINDOW):
                tick = self.tick
                self.tick += 1
                lanes = np.nonzero(arrivals[w])[0]
                for c in map(int, lanes):
                    self.arrivals_total += 1
                    if c in self.quarantine:
                        # Control-plane quarantine: the delivery is
                        # discarded at ingest.  Like the dropout path the
                        # client still pulls the current version — its
                        # send was refused, its clock wasn't.
                        self.arrivals_quarantined += 1
                        self.client_versions[c] = self.version
                        continue
                    if drops[w, c]:
                        # Chaos dropout: the delivery was lost in flight.
                        # The client still pulls the current version and
                        # keeps working (its send failed, its clock
                        # didn't).
                        self.arrivals_dropped += 1
                        self.client_versions[c] = self.version
                        continue
                    # A full buffer loses one event per arrival: the new
                    # one, or — when the arrival would grow the unique-
                    # client set a fireable cycle needs — the oldest
                    # duplicate-client event (UpdateBuffer's anti-
                    # deadlock eviction).
                    self.buffer_overflow += self.buffer.push(ArrivalEvent(
                        client=c, tick=tick,
                        version=int(self.client_versions[c]),
                        corrupt=bool(corrupts[w, c])))
                    # Delivered (or bounced off a full buffer): either
                    # way the client pulls the current version.
                    self.client_versions[c] = self.version
                if self.buffer.unique_clients() >= k:
                    break

    # -- the cycle -----------------------------------------------------------

    def run_cycle(self, state, train_arrays, malicious) -> Tuple[Any, dict]:
        """One buffered-async server round.  Returns ``(new_state,
        device_metrics)``; the host ingest digest lands in
        :attr:`last_info`."""
        spec = self.spec
        cycle_start_tick = self.tick
        self.advance_until_ready()
        events = self.buffer.take_cycle(self.agg_every)
        staleness = np.asarray(
            [self.version - ev.version for ev in events], np.int32)
        clients = np.asarray([ev.client for ev in events], np.int32)
        ticks = np.asarray([ev.tick for ev in events], np.int32)
        corrupt = np.asarray([ev.corrupt for ev in events], bool)
        mal_host = np.asarray(malicious)[clients]

        if spec.weight_schedule == "cutoff":
            # Host-visible degenerate case the jitted program cannot
            # warn about: every buffered row past the cutoff means an
            # all-zero weight vector — the cycle still runs (the server
            # takes a ZERO step and the version advances; discarding an
            # over-stale batch is the cutoff schedule's contract), but
            # silently stalling training is operator-visible only here.
            from blades_tpu.arrivals.weights import staleness_weights

            if float(np.asarray(staleness_weights(
                    "cutoff", staleness,
                    cutoff=self.weight_cutoff)).sum()) == 0.0:
                import warnings

                warnings.warn(
                    f"async cycle at version {self.version}: every "
                    f"buffered row exceeds weight_cutoff="
                    f"{self.weight_cutoff} (staleness "
                    f"{staleness.tolist()}) — the aggregation batch is "
                    "fully discarded and the server takes a zero step",
                    RuntimeWarning, stacklevel=2)

        data_x, data_y, lengths = train_arrays
        k_agg = cycle_agg_key(self._key_base, self.version)
        if self.state_store is not None:
            # Out-of-core event cohort: gather the K arriving clients'
            # opt rows + data shards host-side (the engine IS the
            # sanctioned host boundary), run the cohort-windowed cycle,
            # scatter the updated rows back.
            from blades_tpu.obs.trace import now
            from dataclasses import replace as _dc_replace

            t0 = now()
            rows = self.state_store.gather(clients)
            if self.data_store is not None:
                # Shard-store gather (FIFO event order is fine — the
                # memmap backend regroups by shard internally); the
                # prefetcher observes data_stage_ms/data_bytes_staged.
                ex, ey, eln = self.data_store.gather(clients)
            else:
                ex = jnp.asarray(np.asarray(data_x)[clients])
                ey = jnp.asarray(np.asarray(data_y)[clients])
                eln = jnp.asarray(np.asarray(lengths)[clients])
            staged = (len(clients) * self.state_store.row_bytes
                      + ex.nbytes + ey.nbytes + eln.nbytes)
            self.store_stats.observe(
                now() - t0, staged,
                self.state_store.device_bytes()
                + 2 * len(clients) * self.state_store.row_bytes
                + ex.nbytes + ey.nbytes + eln.nbytes)
            state = _dc_replace(state, client_opt=rows["client_opt"])
            state, metrics = self._cycle(
                state, ex, ey, eln,
                jnp.asarray(clients), jnp.asarray(ticks),
                jnp.asarray(staleness), jnp.asarray(mal_host),
                jnp.asarray(corrupt), self._key_base, k_agg,
            )
            self.state_store.scatter(clients,
                                     {"client_opt": state.client_opt})
            state = _dc_replace(state, client_opt=None)
        else:
            state, metrics = self._cycle(
                state, data_x, data_y, lengths,
                jnp.asarray(clients), jnp.asarray(ticks),
                jnp.asarray(staleness), jnp.asarray(mal_host),
                jnp.asarray(corrupt), self._key_base, k_agg,
            )
        self.version += 1
        self.last_clients = clients
        self.last_staleness = staleness

        hist = np.bincount(
            np.clip(staleness, 0, spec.staleness_cap + 1),
            minlength=spec.staleness_cap + 2)
        self.last_info = {
            "tick": int(self.tick),
            "events": int(self.agg_every),
            "staleness_mean": float(staleness.mean()),
            "staleness_max": int(staleness.max()),
            # Buckets 0..H plus one ">H" overflow bucket.
            "staleness_hist": [int(v) for v in hist],
            "buffer_fill": int(self.buffer.fill),
            "arrivals_total": int(self.arrivals_total),
            "arrivals_dropped": int(self.arrivals_dropped),
            "buffer_overflow": int(self.buffer_overflow),
            "arrival_seed": int(spec.seed),
            # Deterministic ingest sensor (pure in (seed, tick)): how
            # much virtual time this cycle spent collecting its batch —
            # the ingest_stall watchdog rule's field.
            "cycle_ticks": int(self.tick - cycle_start_tick),
            "arrivals_quarantined": int(self.arrivals_quarantined),
        }
        return state, metrics

    # -- state bootstrap / checkpointing -------------------------------------

    def init_history(self, params) -> jax.Array:
        """The ``RoundState.arrivals`` params-history ring at init."""
        return init_history(params, self.spec.staleness_cap)

    def host_state(self) -> Dict[str, Any]:
        """Deterministic host state for the checkpoint payload; restoring
        it via :meth:`restore_host_state` replays the buffered
        trajectory bit-identically."""
        return {
            "tick": int(self.tick),
            "version": int(self.version),
            "client_versions": [int(v) for v in self.client_versions],
            "buffer": self.buffer.state(),
            "arrivals_total": int(self.arrivals_total),
            "arrivals_dropped": int(self.arrivals_dropped),
            "buffer_overflow": int(self.buffer_overflow),
            # Control-plane live actuator values + quarantine set: the
            # restored engine must resume under the CONTROLLED config,
            # not the spec statics, or the trajectory forks.
            "agg_every": int(self.agg_every),
            "buffer_capacity": int(self.buffer.capacity),
            "weight_cutoff": int(self.weight_cutoff),
            "quarantine": sorted(self.quarantine),
            "arrivals_quarantined": int(self.arrivals_quarantined),
        }

    def restore_host_state(self, payload: Dict[str, Any]) -> None:
        versions = payload["client_versions"]
        if len(versions) != self.num_clients:
            raise ValueError(
                f"checkpointed version vector covers {len(versions)} "
                f"clients, this federation has {self.num_clients}")
        self.tick = int(payload["tick"])
        self.version = int(payload["version"])
        self.client_versions = np.asarray(versions, np.int64)
        # Live actuator values first (pre-control checkpoints carry
        # none — .get falls back to the spec statics), then the buffer
        # at the RESTORED capacity.
        self.agg_every = int(payload.get("agg_every",
                                         self.spec.agg_every))
        self.quarantine = frozenset(
            int(c) for c in payload.get("quarantine") or ())
        self.arrivals_quarantined = int(
            payload.get("arrivals_quarantined", 0))
        cutoff = int(payload.get("weight_cutoff",
                                 self.spec.weight_cutoff))
        if cutoff != self.weight_cutoff:
            self.weight_cutoff = cutoff
            self._build_cycle()
        self.buffer = UpdateBuffer(int(payload.get(
            "buffer_capacity", self.spec.effective_capacity)))
        self.buffer.restore(payload.get("buffer") or [])
        self.arrivals_total = int(payload.get("arrivals_total", 0))
        self.arrivals_dropped = int(payload.get("arrivals_dropped", 0))
        self.buffer_overflow = int(payload.get("buffer_overflow", 0))
        self.last_info = {}

    def cold_reset(self, iteration: int) -> None:
        """Resume WITHOUT a checkpointed arrivals payload (a checkpoint
        from before this subsystem existed): restart the arrival clock
        with the version counter synced to the restored round counter.
        The traffic trajectory is fresh — bit-identity with the original
        run is impossible and the caller warns."""
        self.tick = 0
        self.version = int(iteration)
        self.client_versions = np.full(self.num_clients, int(iteration),
                                       np.int64)
        self.buffer = UpdateBuffer(self.spec.effective_capacity)
        self.arrivals_total = 0
        self.arrivals_dropped = 0
        self.buffer_overflow = 0
        self.agg_every = int(self.spec.agg_every)
        self.quarantine = frozenset()
        self.arrivals_quarantined = 0
        if self.weight_cutoff != int(self.spec.weight_cutoff):
            self.weight_cutoff = int(self.spec.weight_cutoff)
            self._build_cycle()
        self.last_info = {}
