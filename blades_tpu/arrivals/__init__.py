"""Buffered-async execution: serve continuous update traffic, not
lockstep rounds (ROADMAP item 2).

Layout:

- :mod:`.process` — deterministic discrete-time Poisson arrival process,
  realizations pure in ``(seed, tick)``;
- :mod:`.weights` — staleness weight schedules (FedBuff polynomial
  discount and friends);
- :mod:`.buffer` — the host-side bounded arrival buffer (events, not
  rows — trivially checkpointed);
- :mod:`.cycle` — the pure jittable aggregation cycle (per-event local
  rounds against versioned params from the history ring, staleness-
  weighted robust aggregation);
- :mod:`.engine` — the host driver: virtual clock, version vector,
  chaos composition, checkpointable host state.

Configure via ``FedavgConfig.resources(execution="async")`` +
``FedavgConfig.arrivals(...)``; see the README "Async buffered
execution" section.
"""

from blades_tpu.arrivals.buffer import ArrivalEvent, UpdateBuffer  # noqa: F401
from blades_tpu.arrivals.engine import (  # noqa: F401
    AsyncEngine,
    AsyncSpec,
    expected_arrivals_per_sec,
    size_for_target,
)
from blades_tpu.arrivals.process import ArrivalProcess  # noqa: F401
from blades_tpu.arrivals.weights import (  # noqa: F401
    STALENESS_SCHEDULES,
    normalized_row_scale,
    staleness_weights,
)
