"""Host-side bounded arrival buffer: the server's FedBuff accumulator.

Arrivals land here as lightweight EVENTS — ``(client, tick, version,
corrupt)`` — not update rows: the simulator computes each event's update
inside the cycle dispatch that consumes it (against the params version
the client pulled, via the history ring), so the buffer itself is pure
host metadata: a handful of ints, trivially checkpointed next to the
version vector and bit-identically restored.

Bounded-buffer semantics: ``push`` on a full buffer drops ONE event (the
loss is counted as ``buffer_overflow`` by the engine) — but which event
depends on whether the arrival grows the unique-client set.  A full
buffer whose unique-client count is below the cycle size would otherwise
be an ABSORBING state: duplicate-client backlog can only leave via
``take_cycle`` (which needs the very unique clients the full buffer keeps
bouncing), so a new DISTINCT client's arrival evicts the oldest
duplicate-client event instead of being rejected — progress toward a
fireable cycle is always possible.  An arrival whose client is already
buffered is simply rejected (its earlier event is the fresher claim on a
cycle slot anyway).

``take_cycle(k)`` pops the first ``k`` events in FIFO order with one
constraint: at most ONE event per client per cycle — a client arriving
twice before the server fires would otherwise race its own optimizer
state inside one dispatch; the second arrival simply stays buffered for
the next cycle, in its original order.
"""

from __future__ import annotations

from typing import List, NamedTuple, Sequence


class ArrivalEvent(NamedTuple):
    """One buffered arrival.

    ``version`` is the global model version the client's in-flight
    update was computed against (its last pull); staleness at
    aggregation time is ``server_version - version``.  ``corrupt`` marks
    the chaos layer's lane-corruption realization for this delivery
    (pure in ``(fault_seed, tick, client)``)."""

    client: int
    tick: int
    version: int
    corrupt: bool = False


class UpdateBuffer:
    """Bounded FIFO of :class:`ArrivalEvent`."""

    def __init__(self, capacity: int):
        if capacity < 1:
            raise ValueError(f"capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._events: List[ArrivalEvent] = []

    @property
    def fill(self) -> int:
        return len(self._events)

    def push(self, event: ArrivalEvent) -> int:
        """Append; returns the number of events LOST doing so (0 = clean
        insert, 1 = an overflow drop).

        Full-buffer policy (see module docstring): an arrival from a
        client NOT yet buffered evicts the oldest duplicate-client event
        (so the unique-client set can always grow toward a fireable
        cycle — a full buffer below ``k`` unique clients would otherwise
        deadlock); an arrival from an already-buffered client, or a full
        buffer with no duplicates to evict, drops the new event."""
        if len(self._events) < self.capacity:
            self._events.append(event)
            return 0
        clients = [e.client for e in self._events]
        if event.client not in clients:
            counts: dict = {}
            for c in clients:
                counts[c] = counts.get(c, 0) + 1
            for i, e in enumerate(self._events):
                if counts[e.client] > 1:
                    del self._events[i]
                    self._events.append(event)
                    return 1
        return 1

    def take_cycle(self, k: int) -> List[ArrivalEvent]:
        """Pop the first ``k`` events (FIFO) with unique clients; events
        whose client already fired this cycle stay buffered in order.
        Raises if fewer than ``k`` unique-client events are available —
        the engine only fires a cycle once the buffer holds one."""
        taken: List[ArrivalEvent] = []
        seen = set()
        rest: List[ArrivalEvent] = []
        for ev in self._events:
            if len(taken) < k and ev.client not in seen:
                taken.append(ev)
                seen.add(ev.client)
            else:
                rest.append(ev)
        if len(taken) < k:
            raise ValueError(
                f"buffer holds {len(taken)} unique-client event(s), "
                f"cycle needs {k}")
        self._events = rest
        return taken

    def unique_clients(self) -> int:
        return len({ev.client for ev in self._events})

    # -- checkpointing -------------------------------------------------------

    def state(self) -> List[List[int]]:
        """JSON/pickle-able buffer contents (ordered)."""
        return [[int(e.client), int(e.tick), int(e.version),
                 bool(e.corrupt)] for e in self._events]

    def restore(self, rows: Sequence[Sequence]) -> None:
        self._events = [
            ArrivalEvent(int(c), int(t), int(v), bool(corr))
            for c, t, v, corr in rows
        ]
        if len(self._events) > self.capacity:
            raise ValueError(
                f"restored {len(self._events)} events into a buffer of "
                f"capacity {self.capacity}")
