"""FedAvg-DP (ref: blades/algorithms/fedavg/fedavg_dp.py).

Differential-privacy variant: computes the Gaussian noise multiplier from
(epsilon, delta, sensitivity) exactly as the reference —
``noise_factor = sensitivity * sqrt(2 * ln(1.25/delta)) / epsilon``
(ref: fedavg_dp.py:22-45) — and turns on the FedRound's per-client
clip+noise path (ref: blades/clients/dp_client.py:32-43).
"""

from __future__ import annotations

import math

from blades_tpu.algorithms.config import FedavgConfig
from blades_tpu.algorithms.fedavg import Fedavg


class FedavgDPConfig(FedavgConfig):
    def __init__(self, algo_class=None):
        super().__init__(algo_class or Fedavg)
        # ref: fedavg_dp.py:16-18 defaults (the canonical YAML grid sweeps
        # eps over {1, 10, 100}, ref: fedavg_dp.yaml:42-44).
        self.dp_epsilon: float = 1.0
        self.dp_delta: float = 1e-6
        self.dp_clip_threshold: float = 1.0

    def privacy(self, *, epsilon=None, delta=None, clip_threshold=None):
        return self._set(dp_epsilon=epsilon, dp_delta=delta,
                         dp_clip_threshold=clip_threshold)

    @property
    def noise_factor(self) -> float:
        """(ref: fedavg_dp.py:44-46: sensitivity = 2 * clip / train_bs;
        sigma = sensitivity * sqrt(2 ln(1.25/delta)) / epsilon, ref: :23-27.
        Returned normalised by the clip because FedRound scales by
        clip * factor — the product is exactly the reference's sigma.)"""
        sensitivity = 2.0 * self.dp_clip_threshold / self.train_batch_size
        sigma = sensitivity * math.sqrt(2.0 * math.log(1.25 / self.dp_delta)) / self.dp_epsilon
        return sigma / self.dp_clip_threshold

    def validate(self) -> None:
        super().validate()
        if self.dp_epsilon <= 0 or not (0 < self.dp_delta < 1):
            raise ValueError("DP requires epsilon > 0 and 0 < delta < 1")
        self.dp_noise_factor = self.noise_factor
