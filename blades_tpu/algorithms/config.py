"""Fluent algorithm config (ref: fllib/algorithms/algorithm_config.py).

Same builder surface as the reference — ``.data() .training() .client()
.adversary() .evaluation() .resources()`` each returning ``self``, a dict
shim (``__getitem__``/``get``/``items``/``update_from_dict``) so YAML
sweeps can treat configs as dicts, ``validate()`` + ``freeze()`` before
``build()`` — but the payload drives the TPU stack: TaskSpec, Server,
FedRound, mesh.
"""

from __future__ import annotations

import copy
from typing import Any, Dict, Optional

from blades_tpu.adversaries import get_adversary
from blades_tpu.core import FedRound, Server, TaskSpec

_INPUT_SHAPES = {
    "mnist": (28, 28, 1),
    "fashionmnist": (28, 28, 1),
    "cifar10": (32, 32, 3),
    "cifar100": (32, 32, 3),
}
_NUM_CLASSES = {"mnist": 10, "fashionmnist": 10, "cifar10": 10, "cifar100": 100}


class FedavgConfig:
    """Builder for :class:`~blades_tpu.algorithms.fedavg.Fedavg`."""

    def __init__(self, algo_class=None):
        from blades_tpu.algorithms import fedavg as _fedavg

        self.algo_class = algo_class or _fedavg.Fedavg
        # data (ref: algorithm_config.py:54-96 defaults)
        self.dataset: Any = "mnist"
        self.num_clients: int = 10
        self.iid: bool = True
        self.dirichlet_alpha: float = 0.1
        self.seed: int = 122  # canonical seed (ref: fedavg_dp.yaml:7-9)
        # model/task
        self.global_model: Any = "mlp"
        self.num_classes: int = 10
        self.input_shape: Optional[tuple] = None
        # client training (ref: client_config.py)
        self.client_lr: float = 0.1
        self.client_momentum: float = 0.0
        self.num_batch_per_round: int = 1  # ref: algorithm_config.py:63
        self.train_batch_size: int = 32
        # benign grad-norm clipping callback (ref: blades/clients/
        # callbacks.py:10-15); None disables
        self.clip_gradient_norm: Optional[float] = None
        # generic client callback chain: list of {"type": ...} specs
        # (ref: fllib/clients/callbacks.py ClientCallbackList)
        self.client_callbacks: Optional[list] = None
        # server (ref: server_config.py)
        self.aggregator: Any = {"type": "Mean"}
        self.server_lr: float = 0.1
        self.server_momentum: float = 0.0
        self.server_dampening: float = 0.0
        self.server_weight_decay: float = 0.0
        self.lr_schedule: Optional[list] = None
        # adversary (ref: blades/algorithms/fedavg/fedavg.py:33-58)
        self.num_malicious_clients: int = 0
        self.adversary_config: Optional[Dict] = None
        # evaluation (ref: algorithm_config.py evaluation_interval)
        self.evaluation_interval: int = 50
        # cap on test rows evaluated PER CLIENT (None = the full per-client
        # test shard).  At 1000 clients the full sharded test set doubles
        # device memory and eval cost for little metric benefit.
        self.evaluation_num_samples: Optional[int] = None
        # dp (ref: blades/clients/dp_client.py) — set via FedavgDPConfig
        self.dp_clip_threshold: Optional[float] = None
        self.dp_noise_factor: Optional[float] = None
        # train-time augmentation; "auto" = by dataset (cifar10 -> crop+flip)
        self.augment: Any = "auto"
        # mixed-precision compute dtype (e.g. "bfloat16"); params stay f32
        self.compute_dtype: Any = None
        # rounds fused per device dispatch (lax.scan); 1 = round-per-call
        self.rounds_per_dispatch: int = 1
        # chained_dispatch: with rounds_per_dispatch > 1, derive each
        # scanned round's key by split-chaining the driver's carry
        # (multi_step_chained) instead of multi_step's one-shot
        # split(key, n) fan.  Rounds are then bit-identical to
        # round-per-dispatch execution — the sweep's scan-window mode
        # sets this.  Dense/streamed single-chip paths only.
        self.chained_dispatch: bool = False
        # round-pipeline perf layer (blades_tpu/perf):
        # donate_buffers: donate RoundState into each dense dispatch —
        # the stacked client opt states are updated in place instead of
        # copied (halves peak HBM for the largest tensors on that path).
        # Callers must then treat the pre-step state as consumed; see
        # README "Performance".  False restores copying semantics.
        self.donate_buffers: bool = True
        # prefetch: stage the next round's per-client batches while the
        # current round computes (data/prefetch.py).  "auto" (default)
        # = on for the dense single-round dispatch on an accelerator
        # backend (CPU has no overlap to win, so auto skips the second
        # program there); True forces, False disables.  Bit-transparent
        # either way.
        self.prefetch: Any = "auto"
        # execution path: "auto" | "dense" | "streamed" | "dsharded" |
        # "async".  "streamed" runs the single-chip streaming round
        # (parallel/streamed.py) whose bf16 (n, d) update matrix + block
        # dispatches fit giant federations in one chip's HBM; "auto"
        # picks it when the dense f32 matrix would strain HBM (> ~6 GB)
        # and no mesh is requested.  "async" replaces lockstep rounds
        # with buffered-async execution (blades_tpu/arrivals): a
        # deterministic Poisson arrival process drives clients that
        # compute against the global model version they last pulled, and
        # the server fires a staleness-weighted robust aggregation every
        # K buffered arrivals (configure via .arrivals()).
        self.execution: str = "auto"
        self.client_block: int = 50        # clients per streamed dispatch
        self.d_chunk: int = 1 << 17        # coords per streamed agg chunk
        self.update_dtype: str = "bfloat16"  # streamed matrix storage
        # MXU finish variant for the streamed pallas finish
        # (ops/pallas_round.py): None = defer to the
        # BLADES_TPU_MXU_FINISH env default, "" = VPU reductions,
        # "counts" = radix counts on the MXU (bit-exact), "all" = also
        # the forged-row stats (f32 reassociation ulps).  The env var
        # remains an explicit per-process override over this field.
        self.mxu_finish: Optional[str] = None
        # Execution autotuner (perf/autotune.py): False/"off" disables;
        # True/"on" tunes over the numerics-preserving default tier
        # (bit-identical to the untuned path); "reassociating" also
        # offers dense<->streamed<->packed switches and the stats-MXU
        # finish (documented float-reassociation tolerances).  Winners
        # persist to the on-disk plan cache (autotune_cache_dir /
        # $BLADES_TPU_PLAN_CACHE_DIR).  Explicitly-set knobs (execution,
        # d_chunk, client_packing, mxu_finish, rounds_per_dispatch,
        # prefetch) are never varied — the tuner only resolves what was
        # left at "auto"/default.
        self.autotune: Any = False
        self.autotune_cache_dir: Optional[str] = None
        # Explicit plan pin: a Plan dict (perf.autotune.Plan.as_dict)
        # applied verbatim instead of tuning — how a resumed sweep
        # replays the EXACT plan its checkpoints were written under
        # (no silent re-tune drift mid-trajectory), and how operators
        # pin a plan from tools/show_plan.py output.
        self.tuned_plan: Optional[Dict] = None
        # client lane-packing (parallel/packed.py): fold P clients into
        # one grouped-kernel vmap lane on the dense path.  "off" | "auto"
        # (pack_factor 2 iff the width/divisibility/hook heuristic passes,
        # LOUD warning + unpacked fallback otherwise) | int P >= 2
        # (forced; structural impossibilities raise).  Updates are
        # unpacked to the dense (n, d) matrix before forging/codecs/
        # faults/aggregation, and checkpoints stay layout-free.
        self.client_packing: Any = "off"
        # Out-of-core per-client state (blades_tpu/state): where the
        # persistent per-client rows (optimizer state, codec EF
        # residual) live.  "resident" (default) = today's dense device
        # stack — with state_window=None the round program, pytrees and
        # checkpoints are LITERALLY unchanged.  "host"/"disk" require a
        # participation window (state_window >= 1): only the sampled
        # cohort's rows are device-resident each round; the registered-
        # population remainder lives in pinned host arrays / a sharded
        # memory-mapped store, with the next cohort staged while the
        # current round computes.  All three backends are bit-identical
        # for the same (seed, cohort schedule).
        self.state_store: str = "resident"
        # Participation window: clients sampled (without replacement,
        # pure in the round key) into each round's cohort.  None = full
        # participation with resident stacks (the pre-window program);
        # 0 = STATELESS clients (full participation, per-client
        # optimizer state re-initialized every round — the degenerate
        # case where there is nothing to store); >= 1 = windowed cohort
        # execution (dense single-chip only).  Set via
        # .resources(window=...).
        self.state_window: Optional[int] = None
        # Directory for the "disk" backend's live sharded memmaps
        # (None = a private temp dir, removed when the trial stops).
        # Checkpoints stream their own per-shard files either way.
        self.state_dir: Optional[str] = None
        # Out-of-core TRAINING DATA (blades_tpu/data/store.py): where
        # the per-client (x, y, lengths) partition lives on the
        # windowed / out-of-core-async paths.  "resident" (default)
        # keeps the host numpy stacks and stages cohorts exactly as
        # before — bit-identical by construction.  "memmap" spills the
        # partition to sharded on-disk .npy files (CRC'd manifest,
        # ClientStateStore's shard discipline) and gathers only the
        # cohort's rows per round, so host RSS scales with the COHORT,
        # not the registered population; eval streams the test stack
        # through the device in bounded chunks.  Both backends are
        # bit-identical for the same (seed, cohort schedule).  Ignored
        # (must stay "resident") on the dense full-participation paths,
        # which never stage per-cohort data.
        self.data_store: str = "resident"
        # Directory for the memmap data store's live shards (None = a
        # private temp dir, removed when the trial stops).  A directory
        # whose manifest + CRCs match the partition is REUSED on
        # resume; any mismatch rebuilds the shards from source.
        self.data_dir: Optional[str] = None
        # Streaming-eval chunk size (clients per jitted eval dispatch)
        # when data_store="memmap" — the device holds one chunk of the
        # test stack at a time, never the full population.
        self.eval_chunk_clients: int = 256
        # failure detection / elastic recovery (core/health.py): zero
        # non-finite client lanes, skip non-finite server updates
        self.health_check: bool = False
        # chaos layer (blades_tpu/faults): deterministic fault-injection
        # spec, e.g. {"dropout_rate": 0.3, "num_stragglers": 1,
        # "staleness": 2, "corrupt_rate": 0.01, "corrupt_mode": "nan",
        # "seed": 7}.  Seed defaults to the trial seed.  None disables —
        # the round program is then bit-identical to a faultless build.
        self.fault_config: Optional[Dict] = None
        # comm subsystem (blades_tpu/comm): compressed-update codec spec,
        # e.g. {"type": "quant", "bits": 8} or {"type": "topk",
        # "topk_ratio": 0.01, "error_feedback": True}.  Encode->decode
        # runs inside the jitted round before robust aggregation; per-
        # round comm_bytes_up / codec_bits / compression-ratio metrics
        # are stamped into the obs stream.  None disables — the round
        # program is then bit-identical to a codec-free build (and
        # {"type": "identity"} is a regression-tested no-op).
        self.codec_config: Optional[Dict] = None
        # Aggregation domain under a codec: "f32" (default) decodes the
        # wire payload to dense f32 before the defenses — bit-identical
        # to the pre-wire-domain program; "wire" keeps quantized updates
        # packed (int8 + per-row scales) through the defense statistics
        # (Server.step_wire / streamed_geometry.aggregate_wire) — the
        # hottest traversals read 1 byte/coordinate instead of 4, per-row
        # scales apply algebraically, adversaries still forge post-codec
        # (in the quantized domain; their rows re-enter the same wire).
        # Requires a deferrable codec (identity/quant — identity is a
        # regression-tested bit-identical pass-through), dense
        # single-chip execution, and none of faults/health/forensics/DP.
        # The autotuner's reassociating tier probes this knob
        # (agg_domain in its plan space); the default tier never does.
        self.agg_domain: str = "f32"
        # buffered-async execution (blades_tpu/arrivals): the arrival /
        # buffering / staleness-weighting spec for execution="async",
        # e.g. {"rate": 0.25, "agg_every": 16, "staleness_cap": 8,
        # "weight_schedule": "polynomial"}.  The arrival seed defaults
        # to the trial seed; set an explicit "seed" to pin the traffic
        # realization across a training-seed grid.  None with
        # execution="async" runs the AsyncSpec defaults; setting it
        # WITHOUT execution="async" is a validate()-time error.
        self.async_config: Optional[Dict] = None
        # defense forensics (obs subsystem): per-lane aggregator telemetry
        # + Byzantine detection precision/recall/FPR emitted from inside
        # the jitted round.  Cohort-shaped: the dense round's lanes are
        # registered clients, the windowed round's lanes are the sampled
        # cohort, the async cycle's lanes are buffered arrival events —
        # each row's lane_forensics carries the cohort id-vector that
        # maps lanes back to registered ids.  Single-chip; the
        # streamed/d-sharded paths never materialise per-lane decisions.
        self.forensics: bool = False
        # Client-lifetime ledger (obs/ledger.py): one longitudinal
        # record per registered client (participation/flagged counts,
        # detection-score EWMA, staleness/norm running stats), updated
        # host-side per round.  False disables; True = the "resident"
        # host-RAM backend; "resident"|"disk" select explicitly ("disk"
        # memmaps the columns for 100k+ registered clients).
        self.ledger: Any = False
        # Directory for the disk ledger's live memmap columns (None = a
        # private temp dir, removed when the trial stops).
        self.ledger_dir: Optional[str] = None
        # Watchdog rule overrides (obs/watchdog.py): a list of rule
        # dicts ({"name", "kind", "field", + window/min_points/factor/
        # threshold}) REPLACING the built-in table — the
        # ``--watchdog-rules`` CLI surface.  Unknown keys, unknown
        # kinds and unknown fields fail at validate().  None keeps
        # ``default_rules()``.
        self.watchdog_rules: Optional[list] = None
        # Closed-loop control plane (blades_tpu/control): watchdog
        # events drive bounded, journaled actuator moves (shrink
        # agg_every, grow the arrival buffer / relax the staleness
        # cutoff, quarantine-and-probe ledger suspects, re-run the
        # autotuner).  A dict of ControlPolicy knobs + {"enabled":
        # bool, "rules": {rule-name: actuator-family | "off"}}; set via
        # .control(...).  None disables — rounds are then bit-identical
        # to an uncontrolled build.
        self.control_config: Optional[Dict] = None
        # server root-dataset size for trust-bootstrapped aggregators (FLTrust)
        self.fltrust_root_size: int = 100
        # resources
        self.num_devices: Optional[int] = None
        # Pod-scale 2-D device layout (parallel/mesh.py): a (clients, d)
        # axis pair tiling exactly num_devices chips — client blocks
        # shard along "clients", the hierarchical gather splits along
        # "d".  None keeps the canonical 1-D (clients,) mesh, so every
        # existing multi-chip config is unchanged.  Set via
        # .resources(mesh_shape=(c, dd)).
        self.mesh_shape: Optional[tuple] = None
        # Hierarchical pre-aggregation (execution="hier", ops/preagg.py):
        # the per-shard robust reduction flavor ("bucket" = s-bucketing
        # means, "nnm" = nearest-neighbor mixing) and its one size knob.
        # bucket_size=1 is the identity pre-agg for BOTH flavors — the
        # hierarchical round is then bit-identical to single-chip dense.
        self.preagg: str = "bucket"
        self.bucket_size: int = 1
        # Decentralized gossip federation (execution="gossip",
        # blades_tpu/topology): the peer-graph spec — a dict for
        # TopologyConfig (graph/k/p/graph_seed/mixing; num_nodes is
        # pinned to num_clients) or a bare graph name.  None with
        # execution="gossip" runs the TopologyConfig defaults (ring);
        # setting it WITHOUT execution="gossip" is a validate()-time
        # error.  Set via .topology(...).
        self.topology_config: Optional[Dict] = None
        self._frozen = False
        # Packing decision from the last get_fed_round() resolution
        # (requested/pack_factor/packed_lanes/fallback) — surfaced in
        # sweep trial summaries so operators can tell packed from
        # unpacked runs without reading logs.
        self._packing_decision = None
        # Names of fields whose values were INFERRED by validate() rather
        # than set by the user — retargeting the dataset resets them so a
        # copy()-then-rebuild re-infers instead of keeping stale values
        # (VERDICT r1: the reference freezes after validate for this).
        self._inferred: set = set()
        # Names of fields the USER set (fluent setters / dict merge),
        # as opposed to class defaults.  The execution autotuner's
        # composition contract keys off this: an explicitly-set knob is
        # pinned in the plan space, a defaulted one may be tuned.
        self._explicit: set = set()
        # Scan-window candidates the sweep runner computed for the
        # autotuner (eligible chained windows, descending); private
        # plumbing like _packing_decision.
        self._autotune_windows = None

    # -- fluent setters ------------------------------------------------------

    def _assign(self, k, v):
        """Single field-assignment point for every setter path (fluent
        and dict merge): explicit values beat inferred ones, and
        retargeting the dataset resets fields a previous validate()
        inferred from it (copy() a built cifar10 config, point it at
        mnist, rebuild — stale shape/classes must not survive)."""
        if k == "dataset":
            if "input_shape" in self._inferred:
                self.input_shape = None
                self._inferred.discard("input_shape")
            if "num_classes" in self._inferred:
                self.num_classes = 10
                self._inferred.discard("num_classes")
        setattr(self, k, v)
        self._inferred.discard(k)
        self._explicit.add(k)

    def _set(self, **kw):
        if self._frozen:
            raise RuntimeError("config is frozen (ref: algorithm_config.py freeze)")
        for k, v in kw.items():
            if v is not None:
                self._assign(k, v)
        return self

    def data(self, *, dataset=None, num_clients=None, iid=None,
             dirichlet_alpha=None, seed=None):
        return self._set(dataset=dataset, num_clients=num_clients, iid=iid,
                         dirichlet_alpha=dirichlet_alpha, seed=seed)

    def training(self, *, global_model=None, num_classes=None, input_shape=None,
                 aggregator=None, server_lr=None, server_momentum=None,
                 server_dampening=None, server_weight_decay=None,
                 lr_schedule=None, num_batch_per_round=None,
                 train_batch_size=None):
        return self._set(
            global_model=global_model, num_classes=num_classes,
            input_shape=input_shape, aggregator=aggregator,
            server_lr=server_lr, server_momentum=server_momentum,
            server_dampening=server_dampening,
            server_weight_decay=server_weight_decay, lr_schedule=lr_schedule,
            num_batch_per_round=num_batch_per_round,
            train_batch_size=train_batch_size,
        )

    def client(self, *, lr=None, momentum=None, clip_gradient_norm=None,
               callbacks=None):
        return self._set(client_lr=lr, client_momentum=momentum,
                         clip_gradient_norm=clip_gradient_norm,
                         client_callbacks=callbacks)

    def adversary(self, *, num_malicious_clients=None, adversary_config=None):
        return self._set(num_malicious_clients=num_malicious_clients,
                         adversary_config=adversary_config)

    def evaluation(self, *, evaluation_interval=None, num_samples=None):
        return self._set(evaluation_interval=evaluation_interval,
                         evaluation_num_samples=num_samples)

    def resources(self, *, num_devices=None, execution=None, client_block=None,
                  d_chunk=None, update_dtype=None, compute_dtype=None,
                  client_packing=None, mxu_finish=None, autotune=None,
                  autotune_cache_dir=None, tuned_plan=None,
                  state_store=None, window=None, state_dir=None,
                  data_store=None, data_dir=None, eval_chunk_clients=None,
                  mesh_shape=None, preagg=None, bucket_size=None):
        """``state_store=`` / ``window=`` / ``state_dir=`` configure the
        out-of-core participation-window store (blades_tpu/state):
        ``window`` is the per-round cohort size (``0`` = stateless
        clients, the degenerate case), ``state_store`` where the
        off-cohort rows live (``resident`` | ``host`` | ``disk``).
        ``window=0`` must be passed explicitly — ``_set`` drops
        ``None`` kwargs, so the sentinel distinction is deliberate.
        ``data_store=`` / ``data_dir=`` / ``eval_chunk_clients=`` are
        the TRAINING-DATA analogue (blades_tpu/data/store.py):
        ``memmap`` spills the partition to disk shards and streams
        eval in device-sized chunks."""
        if window is not None:
            self._set(state_window=int(window))
        return self._set(num_devices=num_devices, execution=execution,
                         client_block=client_block, d_chunk=d_chunk,
                         update_dtype=update_dtype,
                         compute_dtype=compute_dtype,
                         client_packing=client_packing,
                         mxu_finish=mxu_finish, autotune=autotune,
                         autotune_cache_dir=autotune_cache_dir,
                         tuned_plan=tuned_plan, state_store=state_store,
                         state_dir=state_dir, data_store=data_store,
                         data_dir=data_dir,
                         eval_chunk_clients=eval_chunk_clients,
                         mesh_shape=mesh_shape,
                         preagg=preagg, bucket_size=bucket_size)

    def fault_tolerance(self, *, health_check=None, faults=None):
        """In-round failure detection / elastic recovery (core/health.py)
        and the chaos layer's fault-injection spec (``faults=`` a dict for
        :class:`blades_tpu.faults.FaultInjector`); the trial-level
        analogue is ``run_experiments(max_failures=)``."""
        return self._set(health_check=health_check, fault_config=faults)

    def arrivals(self, *, rate=None, rate_schedule=None, slow_fraction=None,
                 slow_factor=None, agg_every=None, buffer_capacity=None,
                 staleness_cap=None, weight_schedule=None, weight_power=None,
                 weight_cutoff=None, seed=None, max_ticks_per_cycle=None,
                 ticks_per_sec=None):
        """Buffered-async arrival spec (:class:`blades_tpu.arrivals.
        AsyncSpec`) for ``execution="async"``: the Poisson arrival rate
        (+ schedule / slow-cohort knobs), the FedBuff buffer geometry
        (``agg_every`` K, bounded ``buffer_capacity``), the params-
        history depth (``staleness_cap`` H) and the staleness weight
        schedule.  ``ticks_per_sec`` is a pure CALIBRATION label (virtual
        ticks per wall second) that lets ``updates_per_sec`` targets
        drive buffer/agg_every sizing via
        :func:`blades_tpu.arrivals.size_for_target`; it never enters the
        arrival realization, which stays pure in ``(seed, tick)``.
        Merges into ``async_config``; see the README "Async buffered
        execution" section."""
        spec = dict(self.async_config or {})
        for k, v in (("rate", rate), ("rate_schedule", rate_schedule),
                     ("slow_fraction", slow_fraction),
                     ("slow_factor", slow_factor), ("agg_every", agg_every),
                     ("buffer_capacity", buffer_capacity),
                     ("staleness_cap", staleness_cap),
                     ("weight_schedule", weight_schedule),
                     ("weight_power", weight_power),
                     ("weight_cutoff", weight_cutoff), ("seed", seed),
                     ("max_ticks_per_cycle", max_ticks_per_cycle),
                     ("ticks_per_sec", ticks_per_sec)):
            if v is not None:
                spec[k] = v
        return self._set(async_config=spec or None)

    def observability(self, *, forensics=None, ledger=None, ledger_dir=None,
                      watchdog_rules=None):
        """Defense forensics (per-lane aggregator diagnostics + Byzantine
        detection precision/recall/FPR per round), the client-lifetime
        ledger (``ledger=True`` for the resident backend, ``"disk"`` to
        memmap the columns; ``ledger_dir=`` the disk backend's live
        directory) and the watchdog rule table (``watchdog_rules=`` a
        list of rule dicts replacing ``default_rules()``; the
        ``--watchdog-rules`` CLI flag routes here) — the obs
        subsystem."""
        return self._set(forensics=forensics, ledger=ledger,
                         ledger_dir=ledger_dir,
                         watchdog_rules=watchdog_rules)

    def control(self, *, enabled=None, rules=None, cooldown_rounds=None,
                quarantine_rounds=None, quarantine_max=None,
                max_quarantine_fraction=None, min_agg_every=None,
                agg_every_factor=None, buffer_factor=None,
                max_buffer_capacity=None, cutoff_factor=None,
                max_weight_cutoff=None, min_window=None,
                window_factor=None):
        """Closed-loop control plane (:mod:`blades_tpu.control`):
        watchdog events drive bounded, rate-limited, journaled actuator
        moves.  ``rules=`` maps watchdog rule NAMES to actuator families
        (``agg_every`` | ``buffer`` | ``quarantine`` | ``replan`` |
        ``window`` | ``"off"``), merged over the default table; the
        remaining knobs are :class:`~blades_tpu.control.ControlPolicy`
        bounds and rate limits (``min_window``/``window_factor`` bound
        the out-of-core shrink-only ``window`` family).  Merges into
        ``control_config`` (the ``.arrivals()`` pattern); a bare
        ``.control()`` arms the defaults.  See the README "Control
        plane" section."""
        spec = dict(self.control_config or {})
        for k, v in (("enabled", enabled), ("rules", rules),
                     ("cooldown_rounds", cooldown_rounds),
                     ("quarantine_rounds", quarantine_rounds),
                     ("quarantine_max", quarantine_max),
                     ("max_quarantine_fraction", max_quarantine_fraction),
                     ("min_agg_every", min_agg_every),
                     ("agg_every_factor", agg_every_factor),
                     ("buffer_factor", buffer_factor),
                     ("max_buffer_capacity", max_buffer_capacity),
                     ("cutoff_factor", cutoff_factor),
                     ("max_weight_cutoff", max_weight_cutoff),
                     ("min_window", min_window),
                     ("window_factor", window_factor)):
            if v is not None:
                spec[k] = v
        if not spec:
            spec = {"enabled": True}  # bare .control() arms the defaults
        return self._set(control_config=spec)

    def topology(self, *, graph=None, k=None, p=None, graph_seed=None,
                 mixing=None):
        """Peer-graph spec for ``execution="gossip"``
        (:class:`blades_tpu.topology.TopologyConfig`): the named graph
        family (``ring`` | ``torus`` | ``kregular`` | ``erdos`` |
        ``complete``), its one size knob (``k`` for kregular, ``p`` for
        erdos), the Erdős–Rényi draw seed and the doubly-stochastic
        mixing scheme (``metropolis`` | ``uniform``).  Merges into
        ``topology_config`` (the ``.arrivals()`` pattern); see the
        README "Decentralized gossip federation" section."""
        spec = dict(self.topology_config or {})
        for key, v in (("graph", graph), ("k", k), ("p", p),
                       ("graph_seed", graph_seed), ("mixing", mixing)):
            if v is not None:
                spec[key] = v
        return self._set(topology_config=spec or None)

    def communication(self, *, codec=None, agg_domain=None):
        """Compressed-update codec on the client->server uplink
        (``codec=`` a dict for :class:`blades_tpu.comm.CodecConfig`,
        e.g. ``{"type": "topk", "topk_ratio": 0.01}``) and the
        aggregation domain (``agg_domain="f32"|"wire"`` — "wire" keeps
        quantized payloads packed through the defense statistics); see
        the README "Communication codecs" section for the interaction
        matrix."""
        return self._set(codec_config=codec, agg_domain=agg_domain)

    # -- dict shim (ref: algorithm_config.py:253-293,360-379) ----------------

    _KEYS = None

    def keys(self):
        return [k for k in vars(self) if not k.startswith("_") and k != "algo_class"]

    def __getitem__(self, k):
        return getattr(self, k)

    def get(self, k, default=None):
        return getattr(self, k, default)

    def items(self):
        return [(k, getattr(self, k)) for k in self.keys()]

    def update_from_dict(self, d: Dict[str, Any]) -> "FedavgConfig":
        """Partial-dict merge (ref: algorithm_config.py:397-453).

        Accepts both flat keys and the reference's YAML nesting
        (``dataset_config``, ``client_config``, ``server_config``,
        ``adversary_config``).
        """
        d = copy.deepcopy(dict(d))
        nested_maps = {
            "dataset_config": {"type": "dataset", "num_clients": "num_clients",
                               "iid": "iid", "alpha": "dirichlet_alpha",
                               "train_bs": "train_batch_size",
                               "num_classes": "num_classes", "seed": "seed"},
            "client_config": {"lr": "client_lr", "momentum": "client_momentum",
                              "num_batch_per_round": "num_batch_per_round",
                              "clip_gradient_norm": "clip_gradient_norm",
                              "callbacks": "client_callbacks"},
            "server_config": {"lr": "server_lr", "momentum": "server_momentum",
                              "dampening": "server_dampening",
                              "weight_decay": "server_weight_decay",
                              "aggregator": "aggregator",
                              "lr_schedule": "lr_schedule"},
        }
        for nk, mapping in nested_maps.items():
            sub = d.pop(nk, None)
            if sub:
                for sk, sv in sub.items():
                    if sk in mapping:
                        self._assign(mapping[sk], sv)
                    else:
                        raise KeyError(f"unknown {nk} key {sk!r}")
        if "adversary_config" in d:
            self.adversary_config = d.pop("adversary_config")
        for k, v in d.items():
            if k in self.keys():
                self._assign(k, v)
            else:
                raise KeyError(f"unknown config key {k!r}")
        return self

    # -- validation / build --------------------------------------------------

    def validate(self) -> None:
        """(ref: algorithm_config.py:295-315)"""
        if self.num_malicious_clients > self.num_clients // 2:
            raise ValueError(
                f"num_malicious_clients={self.num_malicious_clients} is a "
                f"majority of num_clients={self.num_clients}; Byzantine "
                "robustness is undefined past 50%"
            )
        if self.num_malicious_clients > 0 and not self.adversary_config:
            raise ValueError("num_malicious_clients > 0 requires adversary_config")
        if isinstance(self.dataset, str):
            name = self.dataset
        elif isinstance(self.dataset, dict):
            name = self.dataset.get("type")  # catalog dict spec
        else:
            name = getattr(self.dataset, "name", None)
        name = name.lower() if isinstance(name, str) else None
        if self.input_shape is None:
            if name in _INPUT_SHAPES:
                self.input_shape = _INPUT_SHAPES[name]
                self._inferred.add("input_shape")
            else:
                raise ValueError(
                    "input_shape could not be inferred; set "
                    ".training(input_shape=...)"
                )
        # A known dataset with a non-10-class label space overrides the
        # default num_classes (a 10-way head on CIFAR-100 is never right).
        if name in _NUM_CLASSES and self.num_classes == 10:
            self.num_classes = _NUM_CLASSES[name]
            self._inferred.add("num_classes")
        if self.execution not in ("auto", "dense", "streamed", "dsharded",
                                  "async", "hier", "gossip"):
            raise ValueError(
                "execution must be auto|dense|streamed|dsharded|async|hier"
                f"|gossip, got {self.execution!r}"
            )
        if self.topology_config and self.execution != "gossip":
            raise ValueError(
                "topology_config is set but execution="
                f"{self.execution!r}: the peer-graph spec only drives the "
                "decentralized gossip path — set "
                ".resources(execution='gossip') or drop .topology(...)"
            )
        if self.execution == "gossip":
            # Build the topology now so a bad (graph, knob) pair fails at
            # validate() time (TopologyConfig.__post_init__ builds the
            # adjacency) — the faults/codec fail-fast discipline.
            self.get_topology()
            for knob, why, flip in (
                (self.codec_config, "update codecs",
                 ".communication(codec=None)"),
                (self.agg_domain != "f32", "wire-domain aggregation",
                 ".communication(agg_domain='f32')"),
                (self.client_packing not in ("off", None),
                 "client lane-packing",
                 ".resources(client_packing='off')"),
                (self.state_window is not None,
                 "the participation-window store",
                 ".resources(window=None)"),
                (self.state_store != "resident",
                 "out-of-core client state",
                 ".resources(state_store='resident')"),
                (self.forensics, "defense forensics",
                 ".observability(forensics=False)"),
                (self.ledger_backend, "the client ledger",
                 ".observability(ledger=False)"),
                (self.control_config, "the control plane",
                 "drop .control()"),
                (int(self.rounds_per_dispatch or 1) != 1,
                 "rounds_per_dispatch > 1", "rounds_per_dispatch=1"),
                (self.chained_dispatch, "chained_dispatch",
                 "chained_dispatch=False"),
                (self.autotune_mode, "the execution autotuner",
                 ".resources(autotune='off')"),
                (self.mesh_shape is not None, "2-D mesh_shape",
                 ".resources(mesh_shape=None)"),
            ):
                if knob:
                    raise ValueError(
                        f"execution='gossip' × {why} is an unsupported "
                        "pair: the decentralized round has no central "
                        "server matrix for that stage to rewrite — set "
                        f"{flip}, or use a server execution path"
                    )
            injector = self.get_fault_injector()
            if injector is not None:
                if injector.needs_stale_buffer:
                    raise ValueError(
                        "execution='gossip' × straggler faults is an "
                        "unsupported pair: the stale ring buffer is a "
                        "server-path process — gossip faults are EDGE "
                        "dropout (dropout_rate/dropout_schedule); set "
                        "num_stragglers=0"
                    )
                if injector.corrupt_rate > 0.0:
                    raise ValueError(
                        "execution='gossip' × corruption faults is an "
                        "unsupported pair: lane corruption models "
                        "server-bound transfers — gossip faults are EDGE "
                        "dropout; set corrupt_rate=0"
                    )
        if self.async_config and self.execution != "async":
            raise ValueError(
                "async_config is set but execution="
                f"{self.execution!r}: the arrival spec only drives the "
                "buffered-async path — set .resources(execution='async') "
                "or drop .arrivals(...)"
            )
        if self.execution == "async":
            # Build the spec now so a bad arrival/buffer/weight knob
            # fails at validate() time (AsyncSpec.__post_init__ range-
            # checks everything) — the faults/codec fail-fast discipline.
            spec = self.get_async_spec()
            if spec.agg_every > self.num_clients:
                raise ValueError(
                    f"async agg_every={spec.agg_every} > num_clients="
                    f"{self.num_clients}: a cycle aggregates at most one "
                    "event per client"
                )
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    "execution='async' × num_devices>1 is an unsupported "
                    "pair: the buffered cycle program has no mesh "
                    "formulation — set .resources(num_devices=None), or "
                    "use a synchronous execution path on the mesh"
                )
            # Defense forensics COMPOSES with async since the cohort-
            # shaped forensics work: the cycle diagnoses the (K, d)
            # event matrix and lanes are re-indexed by the event
            # id-vector (Server.step_buffered_diag).  The remaining
            # gates name the exact pair and the knob that flips it.
            for knob, why, flip in (
                (self.codec_config, "update codecs",
                 ".communication(codec=None)"),
                (self.agg_domain != "f32", "wire-domain aggregation",
                 ".communication(agg_domain='f32')"),
                (self.client_packing not in ("off", None),
                 "client lane-packing",
                 ".resources(client_packing='off')"),
                (self.autotune_mode, "the execution autotuner",
                 ".resources(autotune='off')"),
                (int(self.rounds_per_dispatch or 1) != 1,
                 "rounds_per_dispatch > 1", "rounds_per_dispatch=1"),
                (self.chained_dispatch, "chained_dispatch",
                 "chained_dispatch=False"),
                (self.health_check, "the in-round health check",
                 ".fault_tolerance(health_check=False)"),
                (self.dp_clip_threshold, "client DP",
                 "dp_clip_threshold=None"),
            ):
                if knob:
                    raise ValueError(
                        f"execution='async' × {why} is an unsupported "
                        "pair: the buffered cycle aggregates arrival "
                        "EVENTS, not the lockstep (n, d) round that "
                        f"stage is formulated over — set {flip}, or use "
                        "a synchronous execution path"
                    )
            injector = self.get_fault_injector()
            if injector is not None and injector.num_stragglers:
                raise ValueError(
                    "execution='async' subsumes the straggler fault "
                    "process (staleness is first-class in the arrival "
                    "model); set num_stragglers=0 — dropout and "
                    "corruption compose with async arrivals as-is"
                )
        if self.execution == "dsharded":
            if not self.num_devices or self.num_devices < 2:
                raise ValueError(
                    "execution='dsharded' width-shards the update matrix "
                    "over a mesh; set .resources(num_devices=...) > 1"
                )
            # rounds_per_dispatch > 1 chains k d-sharded rounds in one
            # lax.scan'ed program (parallel/dsharded.dsharded_multi_step).
        # Pod-scale knobs (parallel/hier.py): fail-fast on every
        # structural impossibility, naming the exact pair and the knob
        # that flips it.
        from blades_tpu.ops.preagg import PREAGG_FLAVORS

        if self.preagg not in PREAGG_FLAVORS:
            raise ValueError(
                f"preagg must be one of {PREAGG_FLAVORS}, got "
                f"{self.preagg!r}")
        if not isinstance(self.bucket_size, int) or self.bucket_size < 1:
            raise ValueError(
                f"bucket_size must be an int >= 1, got {self.bucket_size!r}")
        if self.mesh_shape is not None:
            ms = tuple(int(v) for v in self.mesh_shape)
            if len(ms) != 2 or min(ms) < 1:
                raise ValueError(
                    f"mesh_shape must be a (clients, d) pair of positive "
                    f"ints, got {self.mesh_shape!r}")
            self.mesh_shape = ms
            if not self.num_devices or self.num_devices < 2:
                raise ValueError(
                    "mesh_shape × single-chip is an unsupported pair: the "
                    "2-D (clients, d) layout tiles a multi-chip mesh — "
                    "set .resources(num_devices=...) > 1, or drop "
                    "mesh_shape")
            if ms[0] * ms[1] != self.num_devices:
                raise ValueError(
                    f"mesh_shape {ms[0]}x{ms[1]} must tile exactly "
                    f"num_devices={self.num_devices} chips — fix one of "
                    "the two in .resources(...)")
        if self.execution == "hier":
            if not self.num_devices or self.num_devices < 2:
                raise ValueError(
                    "execution='hier' pre-aggregates per chip and gathers "
                    "representatives over a mesh; set "
                    ".resources(num_devices=...) > 1"
                )
            if int(self.rounds_per_dispatch or 1) != 1:
                raise ValueError(
                    "execution='hier' × rounds_per_dispatch>1 is an "
                    "unsupported pair: the hierarchical round is dispatched "
                    "per-round (no chained-scan formulation yet) — set "
                    "rounds_per_dispatch=1, or use a flat mesh path"
                )
        if self.execution == "streamed":
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    "execution='streamed' × num_devices>1 is an unsupported "
                    "pair: streamed is the single-chip giant-federation "
                    "path — set .resources(num_devices=None), or use a "
                    "mesh execution (dsharded/hier) for multi-chip"
                )
            # rounds_per_dispatch > 1 chains k streamed rounds through the
            # dispatch pipeline with no host sync between them
            # (parallel/streamed.streamed_multi_step).
        if self.forensics:
            if self.execution in ("streamed", "dsharded"):
                raise ValueError(
                    f"forensics × execution={self.execution!r} is an "
                    "unsupported pair: the streamed/d-sharded paths never "
                    "materialise the per-lane decisions forensics reports "
                    "— set .resources(execution='dense') (or 'auto' "
                    "within the dense budget), or flip "
                    ".observability(forensics=False)"
                )
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    "forensics × num_devices>1 is an unsupported pair: "
                    "per-lane diagnostics under shard_map would shard "
                    "the lane axis — set .resources(num_devices=None), "
                    "or flip .observability(forensics=False)"
                )
        if self.fault_config:
            # Build the injector now so a bad spec fails at validate()
            # time (FaultInjector.__post_init__ range-checks every knob).
            self.get_fault_injector()
            if self.execution in ("streamed", "dsharded"):
                raise ValueError(
                    "fault injection (fault_config) is only formulated for "
                    "the dense round — the streamed/d-sharded paths never "
                    "materialise the participation mask the masked "
                    "aggregators consume; use execution='dense' (or 'auto' "
                    "within the dense budget) or disable faults"
                )
            if self.num_devices and self.num_devices > 1:
                # The hierarchical path gathers the full update matrix
                # replicated before injection, so the chaos layer
                # composes there — as long as the pre-aggregation keeps
                # matrix height (kept == n) and no straggler ring is
                # configured (the stale buffer is sized per LANE).  The
                # gossip path composes too, with its OWN edge-dropout
                # realization (gated above, not injector.inject).
                if self.execution not in ("hier", "gossip"):
                    raise ValueError(
                        "fault injection × num_devices>1 is an "
                        "unsupported pair on the flat mesh paths: the "
                        "participation mask under shard_map would shard "
                        "the lane axis — set .resources(num_devices=None) "
                        "or .resources(execution='hier'), or drop faults"
                    )
                if self.execution == "hier":
                    injector = self.get_fault_injector()
                    if injector is not None and injector.needs_stale_buffer:
                        raise ValueError(
                            "execution='hier' × straggler faults is an "
                            "unsupported pair: the stale ring buffer is "
                            "sized per lane and has no hierarchical "
                            "formulation — set num_stragglers=0, or run "
                            "single-chip"
                        )
                    if self.preagg == "bucket" and self.bucket_size != 1:
                        raise ValueError(
                            "execution='hier' × fault injection needs an "
                            "identity-height pre-aggregation (bucketing "
                            f"with bucket_size={self.bucket_size} shrinks "
                            "the matrix) — set .resources(bucket_size=1) "
                            "or preagg='nnm', or drop faults"
                        )
        if self.codec_config:
            # Build the codec now so a bad spec fails at validate() time
            # (CodecConfig.__post_init__ range-checks every knob).
            self.get_codec()
            if self.execution in ("streamed", "dsharded"):
                raise ValueError(
                    "update codecs (codec_config) are only formulated for "
                    "the dense round — the streamed/d-sharded paths never "
                    "materialise the full (n, d) matrix the encode->decode "
                    "transform consumes; use execution='dense' (or 'auto' "
                    "within the dense budget) or disable the codec"
                )
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    "update codecs are single-chip for now: top-k selection "
                    "and per-row scales under shard_map would shard the "
                    "lane axis — run the compressed pass without "
                    "num_devices, or disable the codec"
                )
        if self.agg_domain not in ("f32", "wire"):
            raise ValueError(
                f"agg_domain must be 'f32' or 'wire', got "
                f"{self.agg_domain!r}"
            )
        if self.agg_domain == "wire":
            # Fail-fast discipline of faults/codecs: every structural
            # impossibility surfaces here, not at trace time.
            codec = self.get_codec()
            if codec is None or not codec.supports_deferred:
                raise ValueError(
                    "agg_domain='wire' needs a deferrable codec "
                    "(identity or quant int8/int4): the defense "
                    "statistics traverse the PACKED wire payload, and "
                    f"{'no codec' if codec is None else codec.name!r} has "
                    "no packed-integer matrix to aggregate — set "
                    ".communication(codec={'type': 'quant', ...}) or "
                    "keep agg_domain='f32'"
                )
            for knob, why, flip in (
                (self.fault_config, "fault injection",
                 ".fault_tolerance(faults=None)"),
                (self.health_check, "the in-round health check",
                 ".fault_tolerance(health_check=False)"),
                (self.forensics, "defense forensics",
                 ".observability(forensics=False)"),
                (self.dp_clip_threshold, "client DP",
                 "dp_clip_threshold=None"),
            ):
                if knob:
                    raise ValueError(
                        f"agg_domain='wire' × {why} is an unsupported "
                        "pair: that stage rewrites/inspects dense f32 "
                        "rows the wire domain never materializes — set "
                        f"{flip}, or run under "
                        ".communication(agg_domain='f32')"
                    )
            from blades_tpu.parallel.streamed_geometry import (
                WIRE_AGGREGATORS,
            )

            agg = self.get_server().aggregator
            if not isinstance(agg, WIRE_AGGREGATORS):
                raise ValueError(
                    f"aggregator {type(agg).__name__} has no wire-domain "
                    "formulation (aggregate_wire covers "
                    f"{sorted(c.__name__ for c in WIRE_AGGREGATORS)}); "
                    "use agg_domain='f32'"
                )
        # Out-of-core participation-window store (blades_tpu/state):
        # every structural impossibility fails here, never at trace
        # time — the faults/codecs fail-fast discipline.
        from blades_tpu.state.store import STORE_BACKENDS

        if self.state_store not in STORE_BACKENDS:
            raise ValueError(
                f"state_store must be one of {STORE_BACKENDS}, got "
                f"{self.state_store!r}")
        w = self.state_window
        if w is not None and (not isinstance(w, int) or w < 0):
            raise ValueError(
                f"state_window must be None, 0 (stateless) or a positive "
                f"cohort size, got {w!r}")
        if w is None and self.state_store != "resident":
            if self.execution != "async":
                raise ValueError(
                    f"state_store={self.state_store!r} needs a "
                    "participation window: set .resources(window=...) — "
                    "without one there is no cohort to stage (the async "
                    "path alone windows by its event batch instead)")
        if w == 0:
            if self.state_store != "resident":
                raise ValueError(
                    "window=0 is the STATELESS degenerate case — clients "
                    "keep no state, so there is nothing for a "
                    f"{self.state_store!r} store to hold; drop "
                    "state_store or use window >= 1")
            codec = self.get_codec()
            if codec is not None and codec.needs_residual:
                raise ValueError(
                    "window=0 (stateless clients) cannot compose with a "
                    "top-k error-feedback codec: the EF residual is "
                    "persistent per-client state by definition")
            if self.execution not in ("auto", "dense"):
                raise ValueError(
                    "window=0 (stateless clients) is formulated for the "
                    f"dense round only; execution={self.execution!r} "
                    "carries its own per-client state threading")
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    "window=0 (stateless clients) × num_devices>1 is an "
                    "unsupported pair: the mesh rounds thread per-client "
                    "state through their own bodies — set "
                    ".resources(num_devices=None), or drop window=0")
        if w is not None and w >= 1:
            if w > self.num_clients:
                raise ValueError(
                    f"window={w} > num_clients={self.num_clients}: the "
                    "cohort samples without replacement from the "
                    "registered population")
            if self.execution not in ("auto", "dense"):
                raise ValueError(
                    "the participation-window store is formulated for "
                    "the dense single-chip round (the cohort matrix is "
                    f"(window, d)); execution={self.execution!r} has no "
                    "windowed formulation — drop the window or use "
                    "execution='dense'")
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    f"state_window={w} × num_devices>1 is an unsupported "
                    "pair: cohort gather/scatter has no mesh formulation "
                    "— set .resources(num_devices=None), or drop the "
                    "window")
            # Forensics COMPOSES with the window since the cohort-shaped
            # forensics work: the windowed round diagnoses the
            # (window, d) cohort matrix against the cohort-gathered
            # malicious mask, and the driver stamps the cohort
            # id-vector that maps lanes back to registered ids.  The
            # remaining gates name the exact pair and the knob that
            # flips it.
            for knob, why, flip in (
                (self.fault_config, "fault injection (the straggler "
                 "ring and participation mask are keyed by lane, not "
                 "registered id)", ".fault_tolerance(faults=None)"),
                (self.client_packing not in ("off", None),
                 "client lane-packing",
                 ".resources(client_packing='off')"),
                (self.agg_domain != "f32", "wire-domain aggregation",
                 ".communication(agg_domain='f32')"),
                (int(self.rounds_per_dispatch or 1) != 1,
                 "rounds_per_dispatch > 1 (cohort staging happens "
                 "between dispatches)", "rounds_per_dispatch=1"),
                (self.chained_dispatch, "chained_dispatch",
                 "chained_dispatch=False"),
            ):
                if knob:
                    raise ValueError(
                        f"state_window={w} × {why} is an unsupported "
                        f"pair — set {flip}, or run without the "
                        "participation window")
        # Out-of-core TRAINING DATA (blades_tpu/data/store.py): the
        # memmap backend only engages on the paths that stage per-cohort
        # data — windowed dense, or async × out-of-core state.  Same
        # fail-fast discipline as the state store above.
        from blades_tpu.data.store import DATA_STORE_BACKENDS

        if self.data_store not in DATA_STORE_BACKENDS:
            raise ValueError(
                f"data_store must be one of {DATA_STORE_BACKENDS}, got "
                f"{self.data_store!r}")
        if self.data_store == "memmap":
            ooc_async = (self.execution == "async"
                         and self.state_store != "resident")
            if not ((w is not None and w >= 1) or ooc_async):
                raise ValueError(
                    "data_store='memmap' needs a per-cohort staging path: "
                    "set .resources(window=...) >= 1 (windowed dense) or "
                    "execution='async' with an out-of-core state_store — "
                    "the full-participation rounds hold the whole "
                    "partition on device and never stage cohort data")
        elif self.data_dir:
            raise ValueError(
                "data_dir is set but data_store='resident' — set "
                ".resources(data_store='memmap') (data_dir names the "
                "memmap backend's live shard directory) or drop data_dir"
            )
        if not isinstance(self.eval_chunk_clients, int) \
                or self.eval_chunk_clients < 1:
            raise ValueError(
                f"eval_chunk_clients must be an int >= 1, got "
                f"{self.eval_chunk_clients!r}")
        # Client-lifetime ledger (obs/ledger.py): fail-fast on a bad
        # backend value, and name the one structurally impossible pair.
        self.ledger_backend
        if self.ledger_backend:
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    "ledger × num_devices>1 is an unsupported pair: the "
                    "ledger folds per-lane diagnosis host-side and the "
                    "mesh paths never materialise per-lane decisions — "
                    "set .resources(num_devices=None), or flip "
                    ".observability(ledger=False)"
                )
        elif self.ledger_dir:
            raise ValueError(
                "ledger_dir is set but the ledger is disabled — set "
                ".observability(ledger='disk') (ledger_dir names the "
                "disk backend's live directory) or drop ledger_dir"
            )
        # Watchdog rule overrides: build the table now so an unknown
        # key / kind / field fails at validate() time — the
        # faults/codecs fail-fast discipline.
        if self.watchdog_rules is not None:
            self.get_watchdog_rules()
        # Campaign adversaries (adversaries/campaigns.py) schedule their
        # attack over VIRTUAL TIME — only the async engine has a tick
        # clock to ride.
        if self.adversary_config:
            adv = self.get_adversary()
            if getattr(adv, "requires_virtual_time", False) \
                    and self.execution != "async":
                raise ValueError(
                    f"adversary {self.adversary_config.get('type')!r} is a "
                    "campaign attack scheduled over virtual arrival time; "
                    f"execution={self.execution!r} has no tick clock — set "
                    ".resources(execution='async')"
                )
            # Topology-scoped attacks poison per-RECEIVER over the peer
            # graph — only the gossip round has receivers to scope.
            if getattr(adv, "topology_scoped", False) \
                    and self.execution != "gossip":
                raise ValueError(
                    f"adversary {self.adversary_config.get('type')!r} is "
                    "topology-scoped (per-receiver poisoning over the "
                    f"peer graph); execution={self.execution!r} has no "
                    "peer graph — set .resources(execution='gossip')"
                )
        # Closed-loop control plane: build the policy now (unknown keys
        # / bad bounds fail here), then gate the structurally impossible
        # pairs with the exact knob that flips each one.
        policy = self.get_control_policy()
        if policy is not None:
            if int(self.rounds_per_dispatch or 1) != 1:
                raise ValueError(
                    "control × rounds_per_dispatch > 1 is an unsupported "
                    "pair: the controller observes and actuates between "
                    "HOST-VISIBLE rounds, and a fused dispatch gives it "
                    "none — set rounds_per_dispatch=1, or drop .control()"
                )
            if self.execution in ("streamed", "dsharded"):
                raise ValueError(
                    f"control × execution={self.execution!r} is an "
                    "unsupported pair: the controller's sensors ride "
                    "forensics/ledger row fields those paths never "
                    "produce — use execution='dense'/'async', or drop "
                    ".control()"
                )
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    "control × num_devices>1 is an unsupported pair "
                    "(same lane-axis constraint as forensics/ledger) — "
                    "set .resources(num_devices=None), or drop .control()"
                )
            quarantine_armed = policy.quarantine_rounds > 0 and any(
                fam == "quarantine" for _, fam in policy.rule_table)
            if quarantine_armed:
                # Quarantine moves mask clients at the async ingest
                # filter and pick targets from the ledger's reputation
                # ranking over forensics diagnoses — all three are load-
                # bearing.
                for missing, why, flip in (
                    (self.execution != "async",
                     "an async ingest path to mask clients at",
                     ".resources(execution='async')"),
                    (not self.forensics,
                     "per-lane diagnoses to probe against",
                     ".observability(forensics=True)"),
                    (not self.ledger_backend,
                     "the ledger's reputation ranking to pick suspects",
                     ".observability(ledger=True)"),
                ):
                    if missing:
                        raise ValueError(
                            "control quarantine moves need " + why +
                            f" — set {flip}, or disable them with "
                            ".control(quarantine_rounds=0) or "
                            ".control(rules={'fpr_collapse': 'off', "
                            "'reputation_collapse': 'off'})"
                        )
                spec = self.get_async_spec()
                ceiling = int(policy.max_quarantine_fraction
                              * self.num_clients)
                if self.num_clients - ceiling < spec.agg_every:
                    raise ValueError(
                        f"control max_quarantine_fraction="
                        f"{policy.max_quarantine_fraction} could "
                        f"quarantine {ceiling} of {self.num_clients} "
                        f"clients, starving agg_every={spec.agg_every} "
                        "(a cycle buffers at most one event per free "
                        "client) — lower the fraction or agg_every"
                    )
            if self.execution == "async" and self.state_store != "resident" \
                    and any(fam in ("agg_every", "buffer")
                            for _, fam in policy.rule_table):
                raise ValueError(
                    f"control agg_every/buffer moves × state_store="
                    f"{self.state_store!r} is an unsupported pair: the "
                    "out-of-core store sizes its staging rows by the "
                    "initial agg_every, and both families can GROW the "
                    "staged set — map those rules to the shrink-only "
                    "'window' family in .control(rules=...) (bounded by "
                    "min_window/window_factor), map them 'off', or set "
                    "state_store='resident'"
                )
        if self.client_packing not in ("off", "auto", None):
            # Forced int P: structural impossibilities fail at validate()
            # time, the same fail-fast discipline as faults/codecs.  The
            # full model-aware resolution (width heuristic, hook gates)
            # runs in get_fed_round() via resolve_client_packing.
            try:
                p = int(self.client_packing)
            except (TypeError, ValueError):
                raise ValueError(
                    "client_packing must be 'off', 'auto' or an int >= 2, "
                    f"got {self.client_packing!r}"
                )
            if p < 2:
                raise ValueError(
                    f"client_packing int must be >= 2, got {p}"
                )
            if self.num_clients % p:
                raise ValueError(
                    f"client_packing={p} does not divide num_clients="
                    f"{self.num_clients}"
                )
            if self.num_devices and self.num_devices > 1:
                raise ValueError(
                    "client_packing × num_devices>1 is an unsupported "
                    "pair: the grouped-kernel lanes have no mesh "
                    "formulation — set .resources(num_devices=None), or "
                    ".resources(client_packing='off')"
                )
            if self.execution in ("streamed", "dsharded"):
                raise ValueError(
                    "client_packing needs the dense round; execution="
                    f"{self.execution!r} never runs the packed local round"
                )
        if str(self.update_dtype) not in ("bfloat16", "float32"):
            raise ValueError(
                f"update_dtype must be 'bfloat16' or 'float32', got "
                f"{self.update_dtype!r}"
            )
        if self.mxu_finish not in (None, "", "counts", "all"):
            raise ValueError(
                "mxu_finish must be None (env default), '', 'counts' or "
                f"'all', got {self.mxu_finish!r}"
            )
        self.autotune_mode  # fail-fast on a bad autotune value
        if self.autotune_mode:
            # Multi-chip tuning is legal (ISSUE 18): the plan space keeps
            # the config's own mesh resolution as candidates[0] and the
            # reassociating tier offers mesh_shape/collective switches.
            # Only an EXPLICIT execution='hier' pin conflicts — there the
            # path is already chosen and the tuner has nothing mesh-free
            # to baseline against.
            if self.execution == "hier":
                raise ValueError(
                    "autotune × execution='hier' is an unsupported pair: "
                    "the tuner selects INTO the hierarchical path via its "
                    "collective knob (reassociating tier) — set "
                    ".resources(execution='auto') to let it, pin the plan "
                    "via tuned_plan, or disable autotune"
                )
            if self.execution == "dsharded":
                raise ValueError(
                    "autotune × execution='dsharded' is an unsupported "
                    "pair: the plan space has no d-sharded vocabulary (a "
                    "plan would silently rewrite the pin) — set "
                    ".resources(autotune='off'), or drop the explicit "
                    "execution pin"
                )
        if self.tuned_plan is not None:
            # Parse the pin now so a bad plan dict fails at validate()
            # time (same fail-fast discipline as faults/codecs).
            from blades_tpu.perf.autotune import Plan

            Plan.from_dict(self.tuned_plan)
        if self.chained_dispatch and self.num_devices and self.num_devices > 1:
            raise ValueError(
                "chained_dispatch (the sweep's scan-window key discipline) "
                "has no mesh formulation; run without num_devices or drop "
                "chained_dispatch"
            )
        if self.prefetch not in ("auto", "on", "off", True, False):
            raise ValueError(
                f"prefetch must be 'auto', True or False, got "
                f"{self.prefetch!r}"
            )
        if self.d_chunk < 1024:
            raise ValueError(f"d_chunk must be >= 1024, got {self.d_chunk}")
        if self.client_block < 1:
            raise ValueError(f"client_block must be >= 1, got {self.client_block}")
        if self.evaluation_num_samples is not None and self.evaluation_num_samples < 1:
            raise ValueError(
                f"evaluation_num_samples must be >= 1 (or None for the full "
                f"per-client shard), got {self.evaluation_num_samples}"
            )

    @property
    def ledger_backend(self) -> Optional[str]:
        """Normalized client-ledger request: ``None`` (off),
        ``"resident"`` (host-RAM columns; also what ``ledger=True``
        means) or ``"disk"`` (memmapped columns)."""
        v = self.ledger
        if v in (False, None, 0, "off", ""):
            return None
        if v in (True, 1, "on", "resident"):
            return "resident"
        if v == "disk":
            return "disk"
        raise ValueError(
            f"ledger must be off|resident|disk (or bool), got {v!r}"
        )

    @property
    def autotune_mode(self) -> Optional[str]:
        """Normalized autotune request: ``None`` (off), ``"default"``
        (numerics-preserving tier only) or ``"reassociating"`` (opt-in
        tier included)."""
        v = self.autotune
        if v in (False, None, 0, "off", ""):
            return None
        if v in (True, 1, "on", "default"):
            return "default"
        if v == "reassociating":
            return "reassociating"
        raise ValueError(
            f"autotune must be off|on|reassociating (or bool), got {v!r}"
        )

    def freeze(self) -> None:
        self._frozen = True

    def copy(self) -> "FedavgConfig":
        c = copy.deepcopy(self)
        c._frozen = False
        return c

    # sub-config factories (ref: algorithm_config.py:157-208)

    def get_task_spec(self) -> TaskSpec:
        augment = self.augment
        if augment == "auto":
            # Resolve the dataset NAME the same way validate() does — a
            # catalog dict spec (e.g. {"type": "cifar10",
            # "synthetic_noise": ...}) must still enable cifar crop+flip.
            if isinstance(self.dataset, str):
                name = self.dataset
            elif isinstance(self.dataset, dict):
                name = self.dataset.get("type") or ""
            else:
                name = getattr(self.dataset, "name", "") or ""
            augment = "cifar" if str(name).lower() in ("cifar10", "cifar100") else None
        return TaskSpec(
            model=self.global_model, num_classes=self.num_classes,
            input_shape=tuple(self.input_shape), lr=self.client_lr,
            momentum=self.client_momentum, augment=augment,
            compute_dtype=self.compute_dtype,
        )

    def get_server(self) -> Server:
        return Server.from_config(
            aggregator=self.aggregator,
            num_byzantine=self.num_malicious_clients,
            lr=self.server_lr, momentum=self.server_momentum,
            dampening=self.server_dampening,
            weight_decay=self.server_weight_decay,
            lr_schedule_points=self.lr_schedule,
        )

    def get_adversary(self):
        return get_adversary(
            self.adversary_config,
            num_clients=self.num_clients,
            num_byzantine=self.num_malicious_clients,
            num_classes=self.num_classes,
        )

    def get_fault_injector(self):
        """Build the chaos layer's :class:`~blades_tpu.faults.FaultInjector`
        from ``fault_config`` (None when disabled).  The fault-process
        seed defaults to the trial seed so a seed grid sweeps the failure
        realizations too; set an explicit ``seed`` in the spec to pin the
        failure process across a training-seed grid."""
        if not self.fault_config:
            return None
        from blades_tpu.faults import FaultInjector

        spec = dict(self.fault_config)
        spec.setdefault("seed", int(self.seed))
        # YAML-style dropout_schedule lists are normalized (sorted tuple of
        # (int, float) pairs) by FaultInjector.__post_init__ itself.
        return FaultInjector(**spec)

    def get_async_spec(self):
        """Build the buffered-async
        :class:`~blades_tpu.arrivals.AsyncSpec` from ``async_config``
        (None unless ``execution="async"``).  The arrival seed defaults
        to the trial seed so a seed grid sweeps the traffic realizations
        too; set an explicit ``seed`` in the spec to pin the arrival
        process across a training-seed grid."""
        if self.execution != "async":
            return None
        from blades_tpu.arrivals import AsyncSpec

        spec = dict(self.async_config or {})
        spec.setdefault("seed", int(self.seed))
        if spec.get("rate_schedule") is not None:
            spec["rate_schedule"] = tuple(
                tuple(p) for p in spec["rate_schedule"])
        return AsyncSpec(**spec)

    @property
    def control_enabled(self) -> bool:
        """Whether the closed-loop control plane is armed: a
        ``control_config`` dict whose ``enabled`` (default True when the
        dict exists) is truthy."""
        cfg = self.control_config
        if cfg is None:
            return False
        if not isinstance(cfg, dict):
            raise ValueError(
                f"control_config must be a dict, got {type(cfg).__name__}")
        return bool(cfg.get("enabled", True))

    def get_watchdog_rules(self) -> tuple:
        """The watchdog rule table the trial runs under:
        ``watchdog_rules`` overrides resolved through
        :func:`blades_tpu.obs.watchdog.rules_from_config` (which
        fail-fasts on unknown keys/kinds/fields), or the built-in
        ``default_rules()``."""
        from blades_tpu.obs.watchdog import rules_from_config

        return rules_from_config(self.watchdog_rules)

    def get_control_policy(self):
        """Build the control plane's
        :class:`~blades_tpu.control.ControlPolicy` from
        ``control_config`` (None when disarmed)."""
        if not self.control_enabled:
            return None
        from blades_tpu.control import ControlPolicy

        return ControlPolicy.from_config(self.control_config)

    def get_topology(self):
        """Build the gossip path's
        :class:`~blades_tpu.topology.TopologyConfig` from
        ``topology_config`` (None unless ``execution="gossip"``), with
        ``num_nodes`` pinned to ``num_clients`` — on the gossip path
        every client IS a node."""
        if self.execution != "gossip":
            return None
        from blades_tpu.topology import get_topology

        return get_topology(self.topology_config, int(self.num_clients))

    def get_codec(self):
        """Build the comm subsystem's
        :class:`~blades_tpu.comm.CodecConfig` from ``codec_config``
        (None when disabled)."""
        if not self.codec_config:
            return None
        from blades_tpu.comm import get_codec

        return get_codec(self.codec_config)

    def get_client_callbacks(self) -> tuple:
        from blades_tpu.core.callbacks import ClippingCallback, get_callback

        cbs = [get_callback(s) for s in (self.client_callbacks or [])]
        if self.clip_gradient_norm:
            cbs.append(ClippingCallback(float(self.clip_gradient_norm)))
        return tuple(cbs)

    def resolve_augment_for_data(self, fed_round, dataset):
        """'auto' augmentation means "the dataset's canonical train
        transforms" (cifar crop+flip).  The SYNTHETIC fallback is not an
        image distribution — random crops of its Gaussian class patterns
        destroy the signal (measured: benign CIFAR ResNet accuracy
        0.93 -> 0.19) — so auto resolves to none there.  An explicit
        augment= request is honored as given.  Shared by every driver
        that builds a FedRound and then loads data (Fedavg._setup, the
        lane sweeps) — the dataset's synthetic flag is only known after
        loading, which is why this cannot live in get_task_spec().
        """
        if not (getattr(dataset, "synthetic", False)
                and self.augment == "auto"):
            return fed_round
        import dataclasses as _dc

        task = fed_round.task
        task = _dc.replace(task, spec=_dc.replace(task.spec, augment=None))
        return _dc.replace(fed_round, task=task)

    def get_fed_round(self) -> FedRound:
        fr = FedRound(
            task=self.get_task_spec().build(),
            server=self.get_server(),
            adversary=self.get_adversary(),
            batch_size=self.train_batch_size,
            num_batches_per_round=self.num_batch_per_round,
            dp_clip_threshold=self.dp_clip_threshold,
            dp_noise_factor=self.dp_noise_factor,
            client_callbacks=self.get_client_callbacks(),
            # True federation size: ghost lanes from mesh padding (see
            # shard_federation) are sliced out of forging/aggregation.
            num_clients=self.num_clients,
            health_check=self.health_check,
            forensics=self.forensics,
            faults=self.get_fault_injector(),
            codec=self.get_codec(),
            agg_domain=self.agg_domain,
            agg_d_chunk=self.d_chunk,
            # window=0 stateless degenerate case (blades_tpu/state):
            # fresh per-client optimizer state every round.
            stateless_clients=self.state_window == 0,
        )
        # Client lane-packing: resolve "auto"/forced requests against the
        # built model (width heuristic, hook gates) — LOUD fallback under
        # "auto", hard error for an impossible forced P.  The decision is
        # cached for sweep summaries / laned rows (private attr: frozen
        # configs only guard the public fluent setters).
        from blades_tpu.parallel.packed import resolve_client_packing

        fr, self._packing_decision = resolve_client_packing(
            fr, self.client_packing, num_clients=self.num_clients,
            num_devices=self.num_devices, execution=self.execution,
        )
        return fr

    def build(self):
        """(ref: algorithm_config.py:222-251)"""
        self.validate()
        self.freeze()
        return self.algo_class(self)
