"""Fedavg driver (ref: blades/algorithms/fedavg/fedavg.py + fllib
Algorithm).

The Tune-Trainable surface — ``train()`` per round with periodic
evaluation folded into the result dict, ``save_checkpoint``/
``load_checkpoint``, frozen config — without the Trainable inheritance:
this class IS the trainable the sweep runner drives.

Setup replaces the reference's actor/dataset choreography
(ref: fedavg.py:127-201) with: build dataset arrays, build the FedRound
program, optionally shard it over a mesh, jit once.  Checkpoints carry
FULL state — params, server optimizer, aggregator state, stacked client
optimizer states, round counter, RNG key — fixing the reference's
config-only ``__getstate__`` gap (ref: fllib/algorithms/algorithm.py:206-219,
SURVEY.md §5).
"""

from __future__ import annotations

import pickle
import warnings
from dataclasses import replace as _dc_replace
from pathlib import Path
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from blades_tpu.adversaries import make_malicious_mask
from blades_tpu.core import FedRound
from blades_tpu.data import DatasetCatalog
from blades_tpu.obs.trace import Timers, now
from blades_tpu.perf.async_metrics import DEVICE_METRICS_KEY

#: Private row key carrying the round's cohort id-vector (+ per-event
#: staleness on async rows) from _train_raw to _fill_round_metrics —
#: stamped at dispatch time so DEFERRED rows (train_raw + flush) keep
#: their own cohort even after the engine has moved on.  Popped before
#: the row reaches any sink; never schema-visible.
_COHORT_KEY = "_cohort_ids"


class Fedavg:
    """FedAvg with Byzantine clients and a robust server."""

    def __init__(self, config):
        self.config = config
        self._setup()

    # -- setup (ref: fedavg.py:127-201) -------------------------------------

    def _setup(self) -> None:
        cfg = self.config
        self.dataset = DatasetCatalog.get_dataset(
            cfg.dataset, num_clients=cfg.num_clients, iid=cfg.iid,
            alpha=cfg.dirichlet_alpha, seed=cfg.seed,
        )
        self.fed_round: FedRound = cfg.resolve_augment_for_data(
            cfg.get_fed_round(), self.dataset)
        if getattr(self.fed_round.server.aggregator, "expects_trusted_row", False):
            self.fed_round = self._attach_root_data(self.fed_round)
        self.malicious = make_malicious_mask(cfg.num_clients,
                                             cfg.num_malicious_clients)
        self._key = jax.random.PRNGKey(cfg.seed)
        init_key, self._key = jax.random.split(self._key)
        # Out-of-core per-client state (blades_tpu/state): a sync
        # participation window (state_window >= 1), or an async run
        # whose event-cohort opt rows live behind a host/disk store.
        # Either way the per-client stacks must NOT be materialised at
        # init — at the registered populations the store exists for, a
        # dense broadcast would OOM before the store could help.
        sw = getattr(cfg, "state_window", None)
        self._windowed = sw is not None and sw >= 1
        ooc_async = (cfg.execution == "async"
                     and cfg.state_store != "resident")
        self._state_store = None   # ClientStateStore handle (None = off)
        self._state_pf = None      # StatePrefetcher (sync windowed only)
        self._window_prev = None   # (cohort ids, device rows) of round r-1
        self._row_template = None  # one client's persistent-state row
        if self._windowed or ooc_async:
            server_rows = (int(sw) if self._windowed
                           else cfg.get_async_spec().agg_every)
            self.state, self._row_template = self.fed_round.init_windowed(
                init_key, server_rows)
        else:
            self.state = self.fed_round.init(init_key, cfg.num_clients)

        # The windowed/out-of-core paths keep the training shards
        # HOST-resident (cohort rows are gathered per round); every
        # other path stages the full stacks onto the device as before.
        self._host_train = (self.dataset.train.x, self.dataset.train.y,
                            self.dataset.train.lengths)
        if self._windowed or ooc_async:
            self._train_arrays = None
        else:
            self._train_arrays = tuple(jnp.asarray(a)
                                       for a in self._host_train)
        # Out-of-core training data (blades_tpu/data/store): on the
        # cohort-shaped paths the data plane sits behind a DataStore —
        # `resident` reproduces the legacy host-array staging ops
        # bit-for-bit, `memmap` holds the shards as sharded memory-
        # mapped files so host RSS tracks the cohort, not the
        # registration count.  Dense full-participation paths keep
        # their device-resident stacks untouched.
        self._data_store = None  # DataStore handle (None = legacy plane)
        self._data_pf = None     # DataPrefetcher staging adapter
        self._eval_chunk_fn = None  # jitted streaming-eval chunk program
        self._eval_chunks = 0    # chunks walked by the last streaming eval
        if self._windowed or ooc_async:
            from blades_tpu.data.store import make_data_store
            from blades_tpu.data.stream import DataPrefetcher

            self._data_store = make_data_store(
                getattr(cfg, "data_store", "resident"), self._host_train,
                directory=getattr(cfg, "data_dir", None))
            self._data_pf = DataPrefetcher(self._data_store)
        # Streaming eval rides the memmap data plane: the test stack
        # stays HOST-resident and evaluate() walks it in bounded
        # device-sized chunks instead of device-putting it whole.
        streaming_eval = (self._data_store is not None
                          and self._data_store.backend == "memmap")
        if streaming_eval:
            tx = self.dataset.test.x
            ty = self.dataset.test.y
            tln = self.dataset.test.lengths
        else:
            tx = jnp.asarray(self.dataset.test.x)
            ty = jnp.asarray(self.dataset.test.y)
            tln = jnp.asarray(self.dataset.test.lengths)
        cap = cfg.evaluation_num_samples
        if cap is not None and cap < tx.shape[1]:
            # Per-client eval subsample: bounds device memory + eval cost
            # at giant scale.  Shard rows are index-SORTED (partition.py
            # returns np.sort-ed indices), so taking the first rows would
            # bias any non-randomly-ordered test set — draw a seeded
            # random subset of each client's true rows instead.
            import numpy as np

            rng = np.random.default_rng(cfg.seed ^ 0x5EED)
            n = tx.shape[0]
            pick = np.zeros((n, cap), np.int32)
            for i in range(n):
                k = int(tln[i])
                pick[i] = (rng.choice(k, size=cap, replace=False)
                           if k >= cap else np.arange(cap) % max(k, 1))
            if streaming_eval:
                # Host twin of the device subsample below — the memmap
                # plane keeps the test stack off the device entirely.
                tx = np.take_along_axis(
                    tx, pick.reshape((n, cap) + (1,) * (tx.ndim - 2)),
                    axis=1)
                ty = np.take_along_axis(ty, pick, axis=1)
                tln = np.minimum(tln, cap)
            else:
                tx = jnp.take_along_axis(
                    tx,
                    jnp.asarray(pick).reshape((n, cap) + (1,) * (tx.ndim - 2)),
                    axis=1,
                )
                ty = jnp.take_along_axis(ty, jnp.asarray(pick), axis=1)
                tln = jnp.minimum(tln, cap)
        self._test_arrays = (tx, ty, tln)
        if streaming_eval:
            from blades_tpu.data.stream import make_chunk_evaluator

            self._eval_chunk_fn = make_chunk_evaluator(self.fed_round.task)

        # Execution autotuner (perf/autotune.py): resolve the measured
        # plan — or the checkpoint/operator pin, or the cached winner —
        # and materialise it into the config knobs BEFORE the pipeline
        # below reads them.  None when autotune is off: every path then
        # behaves exactly as before.
        self._plan = None
        self._plan_provenance = None
        if getattr(cfg, "autotune_mode", None):
            self._plan, self._plan_provenance = self._resolve_autotune_plan()
            self._apply_plan(self._plan)

        self._chunk = max(1, int(getattr(cfg, "rounds_per_dispatch", 1)))
        # Chained key discipline (multi_step_chained): each scanned round
        # consumes split(carry) exactly like the sequential driver, so
        # windowed rounds are bit-identical to round-per-dispatch ones.
        self._chained = (bool(getattr(cfg, "chained_dispatch", False))
                         and self._chunk > 1)
        self._prefetcher = None   # set by _setup_dense_pipeline when active
        self._cache_wrappers = []  # CachedFunctions feeding the obs counters
        self._async = None        # AsyncEngine under execution="async"
        self._hier_recorder = None  # PassRecorder under execution="hier"
        self._gossip_recorder = None  # PassRecorder under execution="gossip"
        self._topology = None  # NeighborTables under execution="gossip"
        self.mesh = None
        # Client permutation applied to the stacked arrays (d-sharded
        # elision layout); None = natural order.  Checkpoints record it
        # so per-client state realigns across execution modes.
        self._client_order = None
        if cfg.execution == "async":
            # Buffered-async execution (blades_tpu/arrivals): a host
            # engine drives the virtual arrival clock, version vector and
            # bounded buffer; each train() call is one aggregation cycle
            # (one server round).  RoundState gains the (H+1, d) params-
            # history ring so arriving clients compute against the
            # version they actually pulled.
            from blades_tpu.arrivals import AsyncEngine

            if ooc_async:
                # The event cohort's opt rows come from the window
                # store (gathered per cycle, scattered back after);
                # the version vector is already keyed by registered id.
                from blades_tpu.state import make_store

                self._state_store = make_store(
                    cfg.state_store, cfg.num_clients, self._row_template,
                    directory=getattr(cfg, "state_dir", None))
                # Host-resident shards: the engine gathers the event
                # cohort's data rows per cycle.
                self._train_arrays = self._host_train
            self._async = AsyncEngine(
                self.fed_round, cfg.get_async_spec(), cfg.num_clients,
                train_seed=int(cfg.seed),
                fault_injector=cfg.get_fault_injector(),
                state_store=self._state_store,
                data_store=self._data_pf,
                forensics=bool(cfg.forensics),
            )
            self.state = _dc_replace(
                self.state,
                arrivals=self._async.init_history(self.state.server.params))
            self._step = None
            self._evaluate = jax.jit(self.fed_round.evaluate)
        elif self._windowed:
            self._setup_windowed_pipeline()
        elif cfg.execution == "gossip":
            # Decentralized gossip federation (blades_tpu/topology): every
            # node keeps its own params replica; one round = local train →
            # neighborhood exchange → per-node robust aggregation → mixing.
            # Engages on any device count (a 1-chip mesh still runs the
            # per-node program; the all_gathers just carry zero wire cost).
            from blades_tpu.parallel import make_mesh
            from blades_tpu.topology import (gossip_evaluate,
                                             gossip_federation, gossip_step)

            self.mesh = make_mesh(num_devices=cfg.num_devices)
            self._topology = cfg.get_topology()
            # Malicious mask stays REPLICATED and UNPADDED, like hier:
            # gossip_step pads and slices it inside the traced program
            # (dense-mirroring RNG needs the true node count).
            self.state, self._train_arrays = gossip_federation(
                self.mesh, self.state, self._train_arrays
            )
            self._step, self._gossip_recorder = gossip_step(
                self.fed_round, self.mesh, self._topology
            )
            # Evaluation reads the node-0 replica head; test arrays stay
            # in their default (replicated) placement.
            self._evaluate = gossip_evaluate(self.fed_round)
        elif cfg.num_devices and cfg.num_devices > 1:
            from blades_tpu.parallel import make_mesh, shard_federation, sharded_step
            from blades_tpu.parallel.sharded import sharded_evaluate, sharded_multi_step

            self.mesh = make_mesh(num_devices=cfg.num_devices,
                                  mesh_shape=getattr(cfg, "mesh_shape", None))
            use_hier = cfg.execution == "hier"
            use_dsharded = cfg.execution == "dsharded" or (
                cfg.execution == "auto" and self._dsharded_auto()
            )
            mal_prefix = self._dsharded_elision_prefix() if use_dsharded \
                else None
            if mal_prefix:
                # Malicious-lane elision needs every chip's local lanes
                # laid out [f/n_dev malicious | benign]: permute the
                # client axis BEFORE sharding (client identity rides
                # along — data, mask, and per-client test shards move
                # together; opt-state init is client-symmetric).
                from blades_tpu.parallel.dsharded import elision_client_order

                self._client_order = elision_client_order(
                    cfg.num_clients, mal_prefix, cfg.num_devices)
                order = jnp.asarray(self._client_order)
                self._train_arrays = tuple(a[order]
                                           for a in self._train_arrays)
                self._test_arrays = tuple(a[order]
                                          for a in self._test_arrays)
                self.malicious = self.malicious[order]
            if use_hier:
                # Hierarchical path: data + client state shard P(clients),
                # but the malicious mask stays REPLICATED and UNPADDED —
                # hier_step pads and slices it inside the traced program
                # (dense-mirroring RNG needs the true client count).
                from blades_tpu.parallel import replicated_sharding

                self.state, self._train_arrays = shard_federation(
                    self.mesh, self.state, self._train_arrays
                )
                self.malicious = jax.device_put(
                    self.malicious, replicated_sharding(self.mesh))
            else:
                self.state, arrays = shard_federation(
                    self.mesh, self.state,
                    self._train_arrays + (self.malicious,)
                )
                self._train_arrays, self.malicious = arrays[:3], arrays[3]
            _, self._test_arrays = shard_federation(
                self.mesh, self.state, self._test_arrays
            )
            if use_hier:
                from blades_tpu.parallel import hier_step

                self._step, self._hier_recorder = hier_step(
                    self.fed_round, self.mesh,
                    preagg=getattr(cfg, "preagg", "bucket"),
                    bucket_size=int(getattr(cfg, "bucket_size", 1)),
                )
            elif use_dsharded:
                from blades_tpu.parallel.dsharded import (dsharded_multi_step,
                                                          dsharded_step)

                # Width-sharded giant-federation round: per-device memory
                # is n*d/n_dev — the (n, d) matrix never exists anywhere.
                if self._chunk > 1:
                    self._step = dsharded_multi_step(
                        self.fed_round, self.mesh, self._chunk,
                        malicious_prefix=mal_prefix)
                else:
                    self._step = dsharded_step(self.fed_round, self.mesh,
                                               malicious_prefix=mal_prefix)
            elif self._chunk > 1:
                self._step = sharded_multi_step(
                    self.fed_round, self.mesh, self._chunk, donate=False
                )
            else:
                self._step = sharded_step(self.fed_round, self.mesh, donate=False)
            self._evaluate = sharded_evaluate(self.fed_round, self.mesh)
        elif self._use_streamed():
            if (self.fed_round.packing is not None
                    and cfg.client_packing == "auto"):
                # resolve_client_packing can only veto EXPLICIT streamed/
                # dsharded requests; when execution='auto' resolves to
                # streaming here (HBM-driven), the advisory request keeps
                # its loud-fallback contract instead of hard-failing.
                reason = ("'auto' execution resolved to streaming at "
                          f"num_clients={cfg.num_clients} (dense (n, d) "
                          "matrix would strain HBM); lane packing needs "
                          "the dense round")
                warnings.warn(
                    f"client_packing='auto' falling back to unpacked "
                    f"execution: {reason}", RuntimeWarning, stacklevel=2)
                self.fed_round = _dc_replace(self.fed_round, packing=None)
                cfg._packing_decision = {
                    "requested": "auto", "pack_factor": 1,
                    "packed_lanes": cfg.num_clients, "fallback": reason}
            if (cfg.forensics or cfg.fault_config or cfg.codec_config
                    or self.fed_round.packing is not None):
                what = ("forensics" if cfg.forensics
                        else "fault injection" if cfg.fault_config
                        else "the update codec" if cfg.codec_config
                        else "client lane-packing")
                raise ValueError(
                    f"{what} needs the dense round but 'auto' execution "
                    "resolved to streaming (the dense (n, d) matrix would "
                    f"strain HBM at num_clients={cfg.num_clients}); shrink "
                    f"the federation for this pass or disable {what}"
                )
            from blades_tpu.parallel.streamed import streamed_step

            # With bf16 compute the loss casts inputs down anyway — store
            # the resident training images in bf16 and halve their HBM
            # footprint (2.4 GB -> 1.2 GB at 1000 CIFAR clients), which
            # the giant bf16 update matrix needs back.
            cd = self.fed_round.task.spec.compute_dtype
            if cd is not None:
                x, y, ln = self._train_arrays
                self._train_arrays = (x.astype(jnp.dtype(cd)), y, ln)
            streamed_kw = dict(
                client_block=self._streamed_block(),
                d_chunk=cfg.d_chunk,
                mxu_finish=getattr(cfg, "mxu_finish", None),
                update_dtype=getattr(jnp, str(cfg.update_dtype)),
                # self.malicious IS the canonical prefix mask (built via
                # make_malicious_mask above) — lets forged-update rounds
                # skip the dead malicious-lane training blocks.
                malicious_prefix=cfg.num_malicious_clients,
            )
            if self._chunk > 1:
                from blades_tpu.parallel.streamed import streamed_multi_step

                self._step = streamed_multi_step(
                    self.fed_round, self._chunk, chained=self._chained,
                    **streamed_kw)
            else:
                self._step = streamed_step(self.fed_round, **streamed_kw)
            self._evaluate = jax.jit(self.fed_round.evaluate)
        else:
            self._setup_dense_pipeline()

        # Client-lifetime ledger (obs/ledger.py): one longitudinal
        # record per REGISTERED client, folded host-side in
        # _fill_round_metrics from the already-fetched row and the
        # round's cohort id-vector — zero extra device syncs.
        self._ledger = None
        if getattr(cfg, "ledger_backend", None):
            from blades_tpu.obs.ledger import make_ledger

            self._ledger = make_ledger(
                cfg.ledger_backend, cfg.num_clients,
                directory=getattr(cfg, "ledger_dir", None))

        # Closed-loop control plane (blades_tpu/control): the driver
        # owns its OWN watchdog over finalized rows (stamping
        # watchdog_events itself; the sweep's post-hoc watchdog defers
        # to rows already stamped) and applies the controller's
        # journaled actions back to the engine after every round.
        self._controller = None
        self._watchdog = None
        if getattr(cfg, "control_enabled", False):
            from blades_tpu.control import Controller
            from blades_tpu.obs.watchdog import Watchdog

            self._watchdog = Watchdog(cfg.get_watchdog_rules())
            if self._async is not None:
                self._controller = Controller(
                    cfg.get_control_policy(), num_clients=cfg.num_clients,
                    agg_every=int(self._async.agg_every),
                    buffer_capacity=int(self._async.buffer.capacity),
                    weight_cutoff=int(self._async.weight_cutoff),
                    # Out-of-core window actuator: under a state store
                    # the event-cohort size IS the participation
                    # window, and `window` is the one journaled move
                    # allowed to shrink it (agg_every/buffer moves are
                    # validate()-rejected there — see config.py).
                    window=(int(self._async.agg_every)
                            if self._state_store is not None else None),
                    allow_replan=False,  # async × autotune is forbidden
                )
            else:
                # Sync driver: none of the three async actuators exist;
                # a replan is the one live response (dense/windowed
                # single-chip only — the windowed store/prefetcher must
                # not be rebuilt mid-run).
                self._controller = Controller(
                    cfg.get_control_policy(), num_clients=cfg.num_clients,
                    allow_replan=bool(getattr(cfg, "autotune_mode", None)
                                      and self._state_pf is None
                                      and self.mesh is None),
                )

        self.timers = Timers()
        self._iteration = 0
        self._rounds_since_eval = 0
        self._last_eval: Dict = {}
        # Model width, pinned at setup: the codec's host-side byte
        # accounting must not touch self.state later (whose buffers a
        # donated dispatch deletes).
        self._num_params = sum(
            p.size for p in jax.tree.leaves(self.state.server.params))

    def _setup_dense_pipeline(self) -> None:
        """Single-chip dense path with the perf layer (blades_tpu/perf):
        the round program is AOT-compiled through the process-wide
        executable cache (identically-shaped sweep trials compile once),
        the incoming :class:`RoundState` is DONATED into each dispatch
        (the stacked client opt states — the largest tensors on this
        path — are reused in place instead of copied), and with
        ``prefetch`` on, the next round's per-client batches are staged
        by a separately-dispatched sampling program while the current
        round computes.  All three are bit-transparent: aggregates and
        round metrics match the eager ``jax.jit(fr.step)`` path exactly
        (tests/test_perf.py)."""
        from functools import partial

        from blades_tpu.perf import cached_jit

        cfg = self.config
        donate = (0,) if getattr(cfg, "donate_buffers", True) else ()
        fp = self._program_fingerprint()
        self._prefetcher = None
        if self._chunk > 1 and self._chained:
            step_fn = partial(self.fed_round.multi_step_chained,
                              num_rounds=self._chunk)
            key = ("step", "chained", self._chunk, fp)
        elif self._chunk > 1:
            step_fn = partial(self.fed_round.multi_step, num_rounds=self._chunk)
            key = ("step", "multi", self._chunk, fp)
        elif self._resolve_prefetch():
            from blades_tpu.data.prefetch import BatchPrefetcher

            sample = (cached_jit(self.fed_round.sample_round_batches,
                                 key=("sample", fp))
                      if fp else jax.jit(self.fed_round.sample_round_batches))
            self._sample = lambda k: sample(*self._train_arrays, k)
            self._prefetcher = BatchPrefetcher(self._sample)
            if fp:
                self._cache_wrappers = [sample]
            step_fn = self.fed_round.step_prebatched
            key = ("step", "prebatched", fp)
        else:
            step_fn = self.fed_round.step
            key = ("step", "fused", fp)
        if fp:
            self._step = cached_jit(step_fn, key=key, donate_argnums=donate)
            self._evaluate = cached_jit(self.fed_round.evaluate,
                                        key=("evaluate", fp))
            self._cache_wrappers = ([self._step, self._evaluate]
                                    + self._cache_wrappers)
        else:
            # Un-fingerprintable config (callable model/config values):
            # the executable cannot be safely shared across trials, but
            # donation still applies per-trial.
            self._step = jax.jit(step_fn, donate_argnums=donate)
            self._evaluate = jax.jit(self.fed_round.evaluate)

    def _setup_windowed_pipeline(self) -> None:
        """Single-chip participation-window path (blades_tpu/state):
        each round gathers the sampled cohort's state/data rows from
        the store, runs the SAME fused round program the dense path
        jits (at cohort geometry, AOT-cached + donated), and scatters
        the updated rows back; a :class:`~blades_tpu.state.prefetch.
        StatePrefetcher` stages round ``r+1``'s cohort while round
        ``r`` computes (``prefetch`` semantics as on the dense path:
        "auto" = on for accelerator backends, forced either way is
        bit-transparent)."""
        from blades_tpu.perf import cached_jit
        from blades_tpu.state import StatePrefetcher, make_store, sample_cohort

        cfg = self.config
        n, w = cfg.num_clients, int(cfg.state_window)
        self._state_store = make_store(
            cfg.state_store, n, self._row_template,
            directory=getattr(cfg, "state_dir", None))
        self._state_pf = StatePrefetcher(
            self._state_store,
            # Out-of-core data plane: cohort shards ride the state
            # worker through the DataPrefetcher (always built on the
            # windowed path; `resident` reproduces the host-array ops).
            self._data_pf if self._data_pf is not None else self._host_train,
            np.asarray(self.malicious),
            lambda k: sample_cohort(k, n, w),
            async_staging=self._resolve_prefetch(),
        )
        donate = (0,) if getattr(cfg, "donate_buffers", True) else ()
        fp = self._program_fingerprint()
        if fp:
            self._step = cached_jit(self.fed_round.step,
                                    key=("step", "windowed", fp),
                                    donate_argnums=donate)
            self._evaluate = cached_jit(self.fed_round.evaluate,
                                        key=("evaluate", fp))
            self._cache_wrappers = [self._step, self._evaluate]
        else:
            self._step = jax.jit(self.fed_round.step, donate_argnums=donate)
            self._evaluate = jax.jit(self.fed_round.evaluate)

    def _resolve_prefetch(self) -> bool:
        """``prefetch='auto'`` resolves to ON for the dense single-round
        dispatch (the path with a per-round sampling stage to overlap)
        on an accelerator backend; ``rounds_per_dispatch > 1`` samples
        inside the scan, where there is nothing left to stage, and the
        single-threaded CPU backend has no transfer/compute overlap to
        win — there 'auto' skips the second program's compile.  ``True``
        forces it anywhere (the bit-identity tests do)."""
        want = getattr(self.config, "prefetch", "auto")
        if want in (False, "off"):
            return False
        if self._chunk != 1:
            return False
        if want in (True, "on"):
            return True
        return jax.default_backend() != "cpu"

    def _program_fingerprint(self) -> Optional[str]:
        """Static-config fingerprint for the AOT executable cache
        (:mod:`blades_tpu.perf.compile_cache`).

        Must cover every value the traced round program bakes in as a
        constant.  ``seed`` is excluded on purpose — it only steers data
        values and PRNG key values, both runtime arguments — which is
        exactly what lets a seed grid share one executable.  Dataset
        objects contribute their name only (their arrays are arguments
        too), EXCEPT FLTrust's root data, which the program closes over
        and is therefore digested by value.  Returns ``None`` when the
        config holds values a stable fingerprint cannot capture
        (callables), disabling cross-trial sharing for that trial.
        """
        from blades_tpu.perf import fingerprint

        def plain(v) -> bool:
            # Recursive: a nested custom object (e.g. a callback INSTANCE
            # in client_callbacks) would stringify to a memory-address
            # repr — which a recycled allocation could collide on,
            # silently serving another trial's executable.  Only plainly
            # JSON-able values may enter the fingerprint.
            if isinstance(v, (str, int, float, bool, type(None))):
                return True
            if isinstance(v, (list, tuple)):
                return all(plain(x) for x in v)
            if isinstance(v, dict):
                return all(isinstance(k, str) and plain(x)
                           for k, x in v.items())
            return False

        items: Dict = {"__class__": type(self).__name__,
                       "__augment__": str(self.fed_round.task.spec.augment)}
        for k, v in self.config.items():
            if k == "seed":
                continue
            if k in ("autotune", "autotune_cache_dir", "tuned_plan"):
                # The autotune REQUEST steers nothing in the traced
                # program — the knobs a resolved plan materialises
                # (execution, d_chunk, ...) are ordinary config fields
                # already in this fingerprint.  Excluding the request
                # lets the tuner's measurement candidates share their
                # compiled executables with the winning plan's real run,
                # and gives the plan cache a pre-resolution key.
                continue
            if k == "dataset" and not isinstance(v, (str, dict)):
                v = f"<dataset:{getattr(v, 'name', type(v).__name__)}>"
            if not plain(v):
                return None
            items[k] = v
        td = self.fed_round.trusted_data
        if td is not None:
            import hashlib

            h = hashlib.sha1()
            for a in td:
                h.update(np.asarray(a).tobytes())
            items["__trusted_digest__"] = h.hexdigest()
        return fingerprint(items)

    # Fallback dense-matrix budget when the device will not say how much
    # HBM it has: a dense f32 (n, d) update matrix past this strains one
    # 16 GB chip once training temps and data join it — the
    # giant-federation regime both memory-economical paths exist for.
    _DENSE_MATRIX_HBM_LIMIT = 6 * (1 << 30)
    # Fraction of the device's reported HBM granted to the dense matrix
    # (6 GB / 16 GB, the tuned operating point).
    _DENSE_MATRIX_HBM_FRACTION = 3 / 8

    @classmethod
    def dense_matrix_hbm_limit(cls) -> int:
        """The 'auto'-execution dense budget, device-derived where
        possible (VERDICT r2/r3: a hardcoded 6 GB would stream long
        before necessary on 32/95 GB chips).

        Resolution order: ``BLADES_TPU_DENSE_MATRIX_LIMIT_GB`` env
        override -> 3/8 of ``jax.devices()[0].memory_stats()``'s
        ``bytes_limit`` -> the 16 GB-chip fallback (memory_stats returns
        None through remote-execution relays and on CPU).
        """
        import os

        env = os.environ.get("BLADES_TPU_DENSE_MATRIX_LIMIT_GB")
        if env:
            return int(float(env) * (1 << 30))
        try:
            stats = jax.devices()[0].memory_stats()
            limit = (stats or {}).get("bytes_limit")
            if limit:
                return int(limit * cls._DENSE_MATRIX_HBM_FRACTION)
        except Exception:
            pass
        return cls._DENSE_MATRIX_HBM_LIMIT

    def _dense_matrix_bytes(self) -> int:
        d = sum(p.size for p in jax.tree.leaves(self.state.server.params))
        return self.config.num_clients * d * 4

    def _dsharded_elision_prefix(self):
        """Malicious-lane training elision on the d-sharded path: sound
        exactly when every malicious lane's update is REPLACED by a
        forge computed from benign statistics (update-forging
        adversaries; training-side attacks train for real), and the
        counts divide the mesh so the strided layout is uniform."""
        from blades_tpu.parallel.streamed import _adv_forges

        cfg = self.config
        f = int(cfg.num_malicious_clients or 0)
        if not f or not _adv_forges(self.fed_round.adversary):
            return None
        # floor(f/n_dev) lanes elide per chip; below one per chip there
        # is nothing to skip, and an all-malicious federation has no
        # benign lanes to train (elision_client_order requires f < n).
        if (cfg.num_clients % cfg.num_devices or f < cfg.num_devices
                or f >= cfg.num_clients):
            return None
        return f

    def _dsharded_auto(self) -> bool:
        """On a mesh, pick the width-sharded round when the replicated
        (n, d) matrix the gather formulations materialise per device
        would strain HBM (dsharded_multi_step covers rounds_per_dispatch
        > 1 since round 5)."""
        return self._dense_matrix_bytes() > self.dense_matrix_hbm_limit()

    def _streamed_supported(self) -> bool:
        """The static half of the streamed-execution gate: does this
        round's aggregator/forger pair have a streamed formulation at
        all?  (Feasibility only — the HBM trigger that makes ``'auto'``
        actually pick it lives in :meth:`_use_streamed`.)"""
        from blades_tpu.parallel.streamed import (
            _COORDWISE_AGGREGATORS,
            _COORDWISE_FORGERS,
            _adv_forges,
        )
        from blades_tpu.parallel.streamed_geometry import (
            STREAMED_ROW_AGGREGATORS,
            streamed_row_forgers,
        )

        fr = self.fed_round
        if not isinstance(
            fr.server.aggregator,
            _COORDWISE_AGGREGATORS + STREAMED_ROW_AGGREGATORS,
        ):
            return False
        if _adv_forges(fr.adversary) and not isinstance(
            fr.adversary, _COORDWISE_FORGERS + streamed_row_forgers()
        ):
            return False
        return True

    def _use_streamed(self) -> bool:
        """Pick the single-chip streaming round (parallel/streamed.py).

        Explicit ``execution='streamed'`` always; ``'auto'`` when the
        dense f32 ``(n, d)`` update matrix would strain a 16 GB chip's
        HBM (> ~6 GB) — the giant-federation regime the streamed path
        exists for."""
        cfg = self.config
        if cfg.execution == "dense":
            return False
        if cfg.execution == "streamed":
            return True
        if getattr(self, "_windowed", False):
            # Participation-window runs compute over the (window, d)
            # cohort matrix — the registered population never strains
            # HBM, so 'auto' must not stream on its account.
            return False
        if getattr(self.fed_round, "stateless_clients", False):
            # window=0 stateless clients are formulated in
            # step_prebatched; the streamed path threads client_opt
            # through its own block loop and would silently train
            # STATEFUL clients — 'auto' must stay dense.
            return False
        if not self._streamed_supported():
            return False
        return self._dense_matrix_bytes() > self.dense_matrix_hbm_limit()

    # -- execution autotuner (perf/autotune.py) ------------------------------

    def _d_chunk_exact(self) -> bool:
        """Whether the streamed finish's output is invariant to the
        ``d_chunk`` partition, bit for bit — the gate that keeps the
        chunk ladder in the autotuner's numerics-preserving tier.

        Chunk-size changes are exact for coordinate-wise aggregators on
        deterministic coordinate-wise forges (every statistic is
        per-column).  They are NOT for: DP (noise keys fold the chunk
        index), Noise/Adaptive forges (per-chunk key folds / draws),
        health checks (chunk-local sanitize keeps different slices of a
        partially-non-finite lane), and the row-geometry aggregators
        (row statistics accumulate in chunk order).  Those rounds keep
        the configured chunk."""
        from blades_tpu.adversaries.update_attacks import (AdaptiveAdversary,
                                                           NoiseAdversary)
        from blades_tpu.parallel.streamed import (_COORDWISE_AGGREGATORS,
                                                  _COORDWISE_FORGERS,
                                                  _adv_forges)

        fr = self.fed_round
        if fr.dp_clip_threshold is not None or fr.health_check:
            return False
        if not isinstance(fr.server.aggregator, _COORDWISE_AGGREGATORS):
            return False
        adv = fr.adversary
        if _adv_forges(adv):
            if isinstance(adv, (AdaptiveAdversary, NoiseAdversary)):
                return False
            if not isinstance(adv, _COORDWISE_FORGERS):
                return False
        return True

    def _plan_space(self, allow_reassociating: bool):
        """Enumerate this trial's legal execution plans (see
        :func:`blades_tpu.perf.autotune.enumerate_plans`).  Every
        per-knob candidate list is ordered current-resolution-first and
        collapses to one entry when the user set the knob explicitly —
        the composition contract ``--autotune`` documents."""
        import os

        from blades_tpu.perf import autotune as at

        cfg = self.config
        explicit = getattr(cfg, "_explicit", set()) or set()
        baseline_streamed = self._use_streamed()
        windowed = getattr(self, "_windowed", False)
        stateless = getattr(self.fed_round, "stateless_clients", False)
        dense_features = (cfg.forensics or cfg.fault_config
                          or cfg.codec_config or windowed or stateless)
        packing = getattr(self.fed_round, "packing", None)
        base_pack = int(packing.pack) if packing is not None else 1

        # Execution paths: forced values pin the list; under "auto" the
        # alternate path is reassociating-tier and only legal when its
        # own constraints hold (dense must fit HBM; streamed needs a
        # formulation and none of the dense-only features).
        if cfg.execution in ("dense", "streamed"):
            execs = [cfg.execution]
        else:
            execs = ["streamed" if baseline_streamed else "dense"]
            if allow_reassociating:
                if (baseline_streamed and not dense_features
                        and self._dense_matrix_bytes()
                        <= self.dense_matrix_hbm_limit()):
                    execs.append("dense")
                elif (not baseline_streamed and self._streamed_supported()
                      and not dense_features and base_pack == 1
                      and not (cfg.num_devices and cfg.num_devices > 1)):
                    execs.append("streamed")
        streamed_in_space = "streamed" in execs

        # Streamed chunk ladder (default tier; exact only when the
        # finish is chunk-invariant, see _d_chunk_exact).
        d_chunks = [int(cfg.d_chunk)]
        if (streamed_in_space and "d_chunk" not in explicit
                and self._d_chunk_exact()):
            d_model = self._num_params if hasattr(self, "_num_params") else \
                sum(p.size for p in jax.tree.leaves(self.state.server.params))
            seen = {min(int(cfg.d_chunk), d_model)}
            for c in at.D_CHUNK_LADDER:
                eff = min(int(c), d_model)
                if eff not in seen:
                    seen.add(eff)
                    d_chunks.append(int(c))

        # MXU finish: the env var is an explicit per-process override,
        # an explicit config value pins it; otherwise the tuner varies
        # it ("counts" is bit-exact — default tier; "all" reassociates
        # the forged-row stats — opt-in tier).
        env_mxu = os.environ.get("BLADES_TPU_MXU_FINISH")
        if env_mxu is not None:
            mxu_modes = [env_mxu]
        elif cfg.mxu_finish is not None:
            mxu_modes = [cfg.mxu_finish]
        else:
            mxu_modes = ["", "counts", "all"]

        # Pack factors (dense only; packing reassociates the per-client
        # convolutions).  The resolved baseline comes first; alternates
        # {2, 4, 8} are probed through resolve_client_packing itself —
        # the SAME resolver the static "auto" heuristic uses, so only
        # structurally-possible factors enter the space (impossible ones
        # drop at enumeration, never at apply time) and the measured
        # tier can out-vote the heuristic's fixed P=2.  Composition
        # contract: a forced int pins trivially, and an EXPLICIT "off"
        # pins too — only "auto" (a standing request to resolve) or the
        # untouched default may be varied.
        packs = [base_pack]
        if (allow_reassociating and "dense" in execs and not windowed
                and not isinstance(cfg.client_packing, int)
                and (cfg.client_packing == "auto"
                     or "client_packing" not in explicit)):
            from blades_tpu.parallel.packed import resolve_client_packing

            for p in (1, 2, 4, 8):
                if p in packs or cfg.num_clients % p:
                    continue
                if p == 1:
                    packs.append(1)
                    continue
                try:
                    stripped = _dc_replace(self.fed_round, packing=None)
                    _, dec = resolve_client_packing(
                        stripped, p, num_clients=cfg.num_clients,
                        num_devices=cfg.num_devices, execution="dense")
                except Exception:
                    continue
                if dec and int(dec.get("pack_factor", 1)) == p:
                    packs.append(p)

        # Scan windows: a pinned rounds_per_dispatch stays pinned; the
        # sweep runner supplies the eligible chained windows
        # (descending, its own current pick first) via
        # _autotune_windows — outside a sweep there is no window
        # machinery to drive, so the space stays at 1.
        rpd = int(getattr(cfg, "rounds_per_dispatch", 1) or 1)
        if rpd != 1:
            windows = [rpd]
        else:
            windows = [int(w) for w in
                       (getattr(cfg, "_autotune_windows", None) or (1,))]

        # Prefetch (dense single-round batch staging, bit-transparent):
        # resolved default first, the flip offered only when left "auto".
        base_pre = (False if cfg.prefetch in (False, "off")
                    else True if cfg.prefetch in (True, "on")
                    else jax.default_backend() != "cpu")
        prefetch_options = [base_pre]
        if cfg.prefetch == "auto" and "prefetch" not in explicit:
            prefetch_options.append(not base_pre)

        # Aggregation domain (dense + codec only): the configured value
        # is the baseline; the reassociating tier additionally offers
        # the wire domain when the codec can defer (quant int8/int4 —
        # identity's wire IS f32, so there is nothing to time) and no
        # f32-domain-only stage (faults/health/forensics/DP) is
        # configured.  Explicit agg_domain pins the list — the standard
        # composition contract.
        agg_domains = [cfg.agg_domain]
        if (allow_reassociating and "dense" in execs and not windowed
                and "agg_domain" not in explicit
                and cfg.agg_domain == "f32" and cfg.codec_config
                and not (cfg.fault_config or cfg.health_check
                         or cfg.forensics or cfg.dp_clip_threshold)):
            from blades_tpu.parallel.streamed_geometry import WIRE_AGGREGATORS

            codec = cfg.get_codec()
            if (codec is not None and codec.supports_deferred
                    and codec.name != "identity"
                    and isinstance(self.fed_round.server.aggregator,
                                   WIRE_AGGREGATORS)):
                agg_domains.append("wire")

        # Participation-window store knobs (blades_tpu/state): the
        # window size is PINNED (varying it changes which cohorts — and
        # therefore which data — each round trains on; that is a
        # different experiment, not a reassociation, and a speed-only
        # tuner would always shrink it).  The store BACKEND is
        # bit-identical by contract but changes the staging pipeline,
        # so the reassociating tier may probe the alternates when the
        # user left it defaulted; an explicit backend pins the list —
        # the standard composition contract.
        state_stores = [cfg.state_store]
        if (allow_reassociating and windowed
                and "state_store" not in explicit):
            for alt in ("host", "resident"):
                if alt not in state_stores:
                    state_stores.append(alt)
        state_windows = [getattr(cfg, "state_window", None)]

        # Pod-scale mesh knobs (ISSUE 18): multi-chip tuning keeps the
        # config's own mesh resolution as candidates[0] — a
        # mesh_shape=None plan never touches the device layout, so every
        # pre-pod plan_id stays byte-identical — and the reassociating
        # tier offers the hierarchical collective (and the 2-D torus
        # that carries it).  The d-sharded formulation has no plan
        # vocabulary: an explicit pin is rejected at validate() time,
        # and an 'auto' resolution to it must fail loudly here rather
        # than be silently retuned onto the flat dense mesh.
        nd = int(cfg.num_devices or 1)
        if nd > 1 and cfg.execution == "auto" and self._dsharded_auto():
            raise ValueError(
                "autotune × execution='auto'-resolved-to-dsharded is an "
                "unsupported pair: the plan space has no d-sharded "
                "vocabulary — pin .resources(execution='dsharded') "
                "without autotune, or shrink the federation into the "
                "dense budget")
        base_ms = getattr(cfg, "mesh_shape", None)
        mesh_shapes = [tuple(base_ms) if base_ms else None]
        collectives = ["ring"]
        if nd > 1 and allow_reassociating:
            hier_ms = tuple(base_ms) if base_ms else (nd, 1)
            if hier_ms not in mesh_shapes:
                mesh_shapes.append(hier_ms)
            collectives.append("hier")

        return at.enumerate_plans(
            executions=execs, d_chunks=d_chunks, mxu_modes=mxu_modes,
            pack_factors=packs, scan_windows=windows,
            prefetch_options=prefetch_options, agg_domains=agg_domains,
            state_stores=state_stores, state_windows=state_windows,
            mesh_shapes=mesh_shapes, collectives=collectives,
            num_devices=nd,
            allow_reassociating=allow_reassociating,
        )

    def _resolve_autotune_plan(self):
        """Resolve this trial's execution plan: the explicit
        ``tuned_plan`` pin, the on-disk plan-cache winner, a measured
        selection (TPU), or the deterministic ranked heuristic (CPU /
        timing unavailable) — in that order.  Returns
        ``(Plan, provenance dict)``; the provenance flows into sweep
        summaries and the schema-registered round fields."""
        from blades_tpu.perf import autotune as at

        cfg = self.config
        mode = cfg.autotune_mode
        pinned = getattr(cfg, "tuned_plan", None)
        if pinned:
            plan = at.Plan.from_dict(pinned)
            return plan, {
                "mode": "pinned", "timed": False, "cache_hit": False,
                "winner": plan.as_dict(), "winner_id": plan.plan_id,
                "candidates": [], "truncated": 0,
            }
        space = self._plan_space(
            allow_reassociating=(mode == "reassociating"))
        cache = at.PlanCache(getattr(cfg, "autotune_cache_dir", None))
        fp = self._program_fingerprint()
        key = at.cache_key(fp, tier=mode) if fp else None
        cache_stale = False
        if key is not None:
            entry = cache.get(key)
            if entry is not None:
                plan = at.Plan.from_dict(entry["plan"])
                if plan in space.candidates:
                    prov = dict(entry.get("provenance") or {})
                    prov.update({"mode": "cache", "cache_hit": True,
                                 "winner": plan.as_dict(),
                                 "winner_id": plan.plan_id})
                    return plan, prov
                # The cached winner is no longer in THIS run's legal
                # space: the fingerprint can't see sweep-level window
                # context (max_rounds / checkpoint_freq shape the
                # eligible scan windows), so a winner tuned under one
                # round budget could carry a rounds_per_dispatch that
                # overshoots another run's stop criterion or skips its
                # checkpoint boundaries.  Re-tune (and overwrite below)
                # rather than apply a plan the current constraints
                # forbid.
                cache_stale = True
        measure = (at.timed_measure_fn(cfg) if at.timing_available()
                   else None)
        plan, prov = at.select_plan(space, measure_fn=measure)
        if cache_stale:
            prov["cache_stale"] = True  # surfaced in sweep summaries
        if key is not None:
            cache.put(key, plan, prov)
        return plan, prov

    def _apply_plan(self, plan) -> None:
        """Materialise the resolved plan into the config knobs the
        pipeline setup below reads, and re-resolve lane packing when the
        plan's pack factor differs from what ``get_fed_round`` built."""
        from blades_tpu.perf.autotune import apply_plan

        cfg = self.config
        apply_plan(cfg, plan)
        packing = getattr(self.fed_round, "packing", None)
        cur = int(packing.pack) if packing is not None else 1
        want = int(plan.client_packing or 1)
        if want == cur:
            return
        fr = _dc_replace(self.fed_round, packing=None)
        if want >= 2:
            from blades_tpu.parallel.packed import resolve_client_packing

            fr, decision = resolve_client_packing(
                fr, want, num_clients=cfg.num_clients,
                num_devices=cfg.num_devices, execution=plan.execution)
            cfg._packing_decision = decision
        else:
            cfg._packing_decision = {
                "requested": cfg.client_packing, "pack_factor": 1,
                "packed_lanes": cfg.num_clients,
                "fallback": "autotune plan selected unpacked execution",
            }
        self.fed_round = fr

    def _streamed_block(self) -> int:
        """Largest divisor of num_clients that is <= the configured
        client_block (the streamed path needs an exact tiling).  A client
        count with no usable divisor (e.g. prime) silently degrading to
        1-client dispatches would be a ~50x slowdown — warn loudly."""
        n, want = self.config.num_clients, max(1, self.config.client_block)
        block = 1
        for b in range(min(want, n), 0, -1):
            if n % b == 0:
                block = b
                break
        if block < max(2, want // 4) and n > want:
            import warnings

            warnings.warn(
                f"num_clients={n} has no divisor near client_block={want}; "
                f"the streamed round degrades to {block}-client dispatches "
                f"({n // block} per round). Pick a client count divisible "
                "by the block (or a block dividing the count).",
                stacklevel=2,
            )
        return block

    def _attach_root_data(self, fed_round: FedRound) -> FedRound:
        """Carve a clean server root dataset for FLTrust (Cao et al.): a few
        rows from every client's training shard, round-robin, up to
        ``fltrust_root_size`` samples."""
        import dataclasses

        import numpy as np

        part = self.dataset.train
        per = max(1, -(-self.config.fltrust_root_size // part.num_clients))
        take = [min(per, int(part.lengths[i])) for i in range(part.num_clients)]
        tx = np.concatenate([part.x[i, : take[i]] for i in range(part.num_clients)])
        ty = np.concatenate([part.y[i, : take[i]] for i in range(part.num_clients)])
        tx = tx[: self.config.fltrust_root_size]
        ty = ty[: self.config.fltrust_root_size]
        return dataclasses.replace(
            fed_round, trusted_data=(jnp.asarray(tx), jnp.asarray(ty))
        )

    # -- Trainable surface (ref: algorithm.py:102-119) ----------------------

    @property
    def iteration(self) -> int:
        return self._iteration

    def adopt_tracer(self, tracer) -> None:
        """Observability layer (obs/trace.py): replace this instance's
        phase timers with the caller's span tracer, so the
        ``training_step`` / ``evaluate`` phases nest inside the
        caller's trial/round spans (ONE tree per trial in the
        ``--trace-dir`` export).  The tracer's ``summary()`` shape is a
        superset of the old ``Timers`` one, so the per-row ``timers``
        field keeps its contract."""
        self.timers = tracer

    @property
    def plan(self):
        """The resolved execution :class:`~blades_tpu.perf.autotune.Plan`
        this instance runs under, or ``None`` when autotune is off."""
        return self._plan

    @property
    def plan_summary(self) -> Optional[Dict]:
        """Autotune provenance for sweep summaries: selection mode
        (measured / heuristic / cache / pinned), per-candidate timings,
        winner and cache hit/miss.  ``None`` when autotune is off."""
        return self._plan_provenance

    @property
    def state_summary(self) -> Optional[Dict]:
        """Out-of-core client-state digest for sweep summaries (backend,
        window, row/total bytes, staging peak), or ``None`` when no
        store is configured."""
        if self._state_store is None:
            return None
        stats = (self._state_pf.stats if self._state_pf is not None
                 else self._async.store_stats)
        return {
            "backend": self._state_store.backend,
            "window": (int(self.config.state_window)
                       if self._state_pf is not None
                       else int(self._async.agg_every)),
            "n_registered": self._state_store.n_registered,
            "row_bytes": int(self._state_store.row_bytes),
            "total_bytes": int(self._state_store.total_bytes()),
            "peak_hbm_bytes": int(stats.peak_hbm_bytes),
        }

    @property
    def data_summary(self) -> Optional[Dict]:
        """Out-of-core training-data digest for sweep summaries
        (backend, population/row bytes, last staging cost, eval
        chunking), or ``None`` when the data plane is the legacy dense
        one."""
        if self._data_store is None:
            return None
        stats = self._data_pf.stats
        return {
            "backend": self._data_store.backend,
            "n_clients": int(self._data_store.n_clients),
            "row_bytes": int(self._data_store.row_bytes),
            "total_bytes": int(self._data_store.total_bytes()),
            "last_stage_ms": round(stats.last_stage_ms, 3),
            "last_bytes_staged": int(stats.last_bytes_staged),
            "eval_chunks": int(self._eval_chunks),
        }

    @property
    def client_ledger(self):
        """The live :class:`~blades_tpu.obs.ledger.ClientLedger`, or
        ``None`` when the ledger is off — the sweep attaches it to the
        flight recorder so dumps carry the fleet fingerprint."""
        return self._ledger

    @property
    def ledger_summary(self) -> Optional[Dict]:
        """Client-ledger fleet digest for sweep summaries (backend,
        clients seen, suspected fraction, reputation percentiles), or
        ``None`` when the ledger is off."""
        if self._ledger is None:
            return None
        return self._ledger.summary()

    @property
    def control_summary(self) -> Optional[Dict]:
        """Closed-loop controller digest for sweep summaries (actions
        journaled, live actuator view, quarantine/probation sets,
        driver-watchdog event count), or ``None`` when control is
        off."""
        if self._controller is None:
            return None
        out = self._controller.summary()
        out["watchdog_events"] = len(self._watchdog.events)
        return out

    @property
    def packing_summary(self) -> Optional[Dict]:
        """The lane-packing decision get_fed_round() resolved for this
        trial (requested/pack_factor/packed_lanes/fallback reason), or
        None when packing was never requested — the sweep mirrors it
        into trial summaries."""
        return getattr(self.config, "_packing_decision", None)

    def _windowed_round(self):
        """One participation-window round: take the staged cohort
        (state rows, data shards, malicious mask), run the fused round
        program over it, then hand the updated rows to the prefetcher
        — the NEXT round's stage job first (it excludes this cohort's
        ids, so it overlaps this round's compute), the write-back
        second (FIFO ordering guarantees any later stage revisiting
        these ids sees it).  Returns the device metrics dict."""
        round_key, self._key = jax.random.split(self._key)
        ids, rows, data, mal = self._state_pf.take(
            self._iteration, round_key, self._window_prev)
        state_in = _dc_replace(
            self.state, client_opt=rows["client_opt"],
            residual=rows.get("residual"), cohort=jnp.asarray(ids))
        new_state, raw_metrics = self._step(state_in, *data, mal, round_key)
        self.state = new_state
        out_rows = {"client_opt": new_state.client_opt}
        if new_state.residual is not None:
            out_rows["residual"] = new_state.residual
        self._state_pf.stage(self._iteration + 1,
                             jax.random.split(self._key)[0], prev_ids=ids)
        self._state_pf.writeback(ids, out_rows)
        self._window_prev = (ids, out_rows)
        return raw_metrics

    def train(self) -> Dict:
        """One training dispatch (= ``rounds_per_dispatch`` FL rounds, 1 by
        default) + periodic eval, returns the last round's result dict."""
        return self.finalize_row(self._train_raw(fetch=True))

    def train_raw(self) -> Dict:
        """One training dispatch WITHOUT the host sync on round-scalar
        metrics: the returned row carries its device metrics under
        ``perf.async_metrics.DEVICE_METRICS_KEY`` and must be passed
        through :meth:`finalize_row` (or ``perf.flush_rows``, which
        batches the ``device_get`` across rows) before it is consumed.
        The async sweep loop (``metrics_every > 1``) drives this."""
        return self._train_raw(fetch=False)

    def _train_raw(self, fetch: bool) -> Dict:
        cycle_t0 = now() if self._async is not None else None
        with self.timers.time("training_step"):
            if self._async is not None:
                # One buffered-async cycle: the engine advances the
                # virtual clock to the next full buffer and fires ONE
                # aggregation dispatch.  The training key chain is
                # untouched — per-event keys are pure in (seed, tick,
                # client), so resume re-derives them from the
                # checkpointed tick alone.
                self.state, raw_metrics = self._async.run_cycle(
                    self.state, self._train_arrays, self.malicious)
            elif self._state_pf is not None:
                raw_metrics = self._windowed_round()
            elif self._chained:
                # The window program advances the key chain itself, one
                # split per scanned round — handing back the carry a
                # sequential driver would hold at the same round.
                self.state, self._key, raw_metrics = self._step(
                    self.state, *self._train_arrays, self.malicious,
                    self._key
                )
            elif self._prefetcher is not None:
                round_key, self._key = jax.random.split(self._key)
                # Staged last dispatch (or drawn now on the first); the
                # NEXT round's batches are dispatched right behind this
                # round's step, overlapping its compute.  The peeked key
                # equals the round key the next train() will split off.
                bx, by = self._prefetcher.take(self._iteration, round_key)
                self.state, raw_metrics = self._step(
                    self.state, bx, by, self.malicious, round_key
                )
                self._prefetcher.stage(self._iteration + self._chunk,
                                       jax.random.split(self._key)[0])
            else:
                round_key, self._key = jax.random.split(self._key)
                self.state, raw_metrics = self._step(
                    self.state, *self._train_arrays, self.malicious, round_key
                )
            if fetch:
                # Concrete fetches inside the timer: block_until_ready
                # alone can return early through remote-execution tunnels.
                raw_metrics = jax.device_get(raw_metrics)
        self._iteration += self._chunk
        self._rounds_since_eval += self._chunk
        row = {
            "training_iteration": self._iteration,
            DEVICE_METRICS_KEY: raw_metrics,
            "timers": self.timers.summary(),
        }
        if self._async is not None:
            # Host-side ingest digest (blades_tpu/arrivals): stamped at
            # row creation — these are host ints the engine already
            # holds, no device fetch to defer.  updates_per_sec is the
            # one wall-clock field (the bench's ingest metric), measured
            # through the span layer's sanctioned clock; everything else
            # is deterministic and replay-comparable.
            info = self._async.last_info
            elapsed = max(now() - cycle_t0, 1e-9)
            row["tick"] = int(info["tick"])
            row["staleness_mean"] = float(info["staleness_mean"])
            row["staleness_max"] = int(info["staleness_max"])
            row["staleness_hist"] = [int(v) for v in info["staleness_hist"]]
            row["buffer_fill"] = int(info["buffer_fill"])
            row["arrivals_dropped"] = int(info["arrivals_dropped"])
            row["buffer_overflow"] = int(info["buffer_overflow"])
            row["arrival_seed"] = int(info["arrival_seed"])
            row["updates_per_sec"] = round(info["events"] / elapsed, 3)
            # Control-plane sensors (blades_tpu/control): virtual ticks
            # this cycle spent ingesting (the deterministic twin of
            # updates_per_sec) and the cumulative quarantine-filtered
            # arrival count — host ints, replay-comparable.
            row["cycle_ticks"] = int(info["cycle_ticks"])
            row["arrivals_quarantined"] = int(info["arrivals_quarantined"])
            # Event cohort: lane i of this cycle's diag/metrics lanes is
            # registered client last_clients[i].  Captured NOW so a
            # deferred row keeps its own cohort after later cycles
            # overwrite the engine's last_* columns.
            row[_COHORT_KEY] = (
                np.asarray(self._async.last_clients, np.int64),
                np.asarray(self._async.last_staleness, np.int64))
        elif self._state_pf is not None and self._window_prev is not None:
            # Sampled window cohort: lane i diagnoses registered client
            # _window_prev[0][i] (set by the round that just ran).
            row[_COHORT_KEY] = (
                np.asarray(self._window_prev[0], np.int64), None)
        if self._state_store is not None:
            # Participation-window staging digest (blades_tpu/state):
            # host counters the staging layer already holds — no device
            # fetch to defer.  state_peak_hbm_bytes is the analytic
            # ceiling on device-resident per-client state (store-held
            # bytes + the staged/live/write-back cohort slots) — the
            # number the memory-ceiling acceptance test pins against a
            # window-proportional bound.
            stats = (self._state_pf.stats if self._state_pf is not None
                     else self._async.store_stats)
            row["state_store"] = self._state_store.backend
            row["cohort_size"] = (int(self.config.state_window)
                                  if self._state_pf is not None
                                  else int(self._async.agg_every))
            row["state_stage_ms"] = round(stats.last_stage_ms, 3)
            row["state_bytes_staged"] = int(stats.last_bytes_staged)
            row["state_peak_hbm_bytes"] = int(stats.peak_hbm_bytes)
        if self._data_store is not None:
            # Out-of-core data staging digest (blades_tpu/data): host
            # counters the DataPrefetcher already holds — no device
            # fetch to defer.  data_bytes_staged is the LAST cohort/
            # event gather's device-put volume, the number the 1M
            # acceptance test pins against a cohort-proportional bound.
            dstats = self._data_pf.stats
            row["data_store"] = self._data_store.backend
            row["data_stage_ms"] = round(dstats.last_stage_ms, 3)
            row["data_bytes_staged"] = int(dstats.last_bytes_staged)
        if self._cache_wrappers:
            # Per-trial AOT compile-cache counters (obs schema fields):
            # cumulative over this trial's dispatches, so the first row
            # already says whether the round program was a hit or a miss.
            row["compile_cache_hits"] = sum(
                w.stats["hits"] for w in self._cache_wrappers)
            row["compile_cache_misses"] = sum(
                w.stats["misses"] for w in self._cache_wrappers)
        # Rounds-since-last-eval cadence: robust to rounds_per_dispatch not
        # dividing evaluation_interval (a modulo test would then never fire).
        if self.config.evaluation_interval and (
            self._rounds_since_eval >= self.config.evaluation_interval
        ):
            self._rounds_since_eval = 0
            row.update(self.evaluate())
        elif self._last_eval:
            row.update(self._last_eval)
        return row

    def finalize_row(self, row: Dict) -> Dict:
        """Convert a (possibly deferred) row's device metrics into the
        host-scalar result dict ``train()`` has always returned.  "lane_"
        keys are per-lane forensics vectors (``(n,)``, stacked to
        ``(rounds, n)`` under ``rounds_per_dispatch``) — kept whole, last
        round reported."""
        raw = row.pop(DEVICE_METRICS_KEY, None)
        if raw is None:
            return row
        raw = jax.device_get(raw)
        self._fill_round_metrics(row, raw, idx=None)
        return row

    def _fill_round_metrics(self, row: Dict, raw: Dict, idx) -> None:
        """Fill ``row`` with the host form of the fetched metrics dict.

        ``idx=None``: the classic dispatch summary — scalars from the
        chunk's LAST round, health counts reduced over the whole chunk (a
        lane that went non-finite mid-chunk must surface even if it
        recovered by the last round).  ``idx=r``: round ``r``'s values
        from a stacked multi-round dispatch (the per-round rows of the
        sweep's scan-window path)."""
        # The round's cohort id-vector (+ per-event staleness on async
        # rows): stamped by _train_raw on the cohort-varying paths,
        # identity arange on the dense full-participation round.
        cohort_ids, cohort_staleness = row.pop(_COHORT_KEY, (None, None))
        metrics, lanes = {}, {}
        for k, v in raw.items():
            a = np.asarray(v)
            if k.startswith("lane_"):
                if a.ndim > 1:
                    a = a[-1 if idx is None else idx]
                lanes[k[len("lane_"):]] = a
            elif a.ndim:
                metrics[k] = float(a[-1 if idx is None else idx])
            else:
                metrics[k] = float(a)
        row["train_loss"] = metrics["train_loss"]
        row["agg_norm"] = metrics["agg_norm"]
        row["update_norm_mean"] = metrics["update_norm_mean"]
        codec = self.fed_round.codec  # comm subsystem (blades_tpu/comm)
        if codec is not None:
            # Static per-round byte accounting, stamped host-side so the
            # device program carries no extra outputs.  Under a
            # participation window only the sampled cohort transmits —
            # the uplink is window rows, not the registered population.
            uplink_rows = (int(self.config.state_window)
                           if self._state_pf is not None
                           else self.config.num_clients)
            row.update(codec.round_metrics(uplink_rows, self._num_params))
            # Aggregation-domain provenance (wire-domain aggregation):
            # which domain the defenses ran in and the storage width of
            # the matrix they traversed (8 = packed int8 wire payload,
            # 32 = dense f32), so A/B rows are separable in telemetry.
            # Static per round, stamped host-side like the bytes above.
            domain = getattr(self.fed_round, "agg_domain", "f32")
            row["agg_domain"] = domain
            row["agg_domain_bits"] = (codec.storage_bits
                                      if domain == "wire" else 32)
        if "dequant_rows" in metrics:
            # Wire-domain decode accounting: full-width f32 rows
            # materialized from the packed payload this round (selected
            # slices + the forge's sanctioned full read) — the honesty
            # counter next to the 1-byte hbm traversals.
            row["dequant_rows"] = int(metrics["dequant_rows"])
        packing = getattr(self.fed_round, "packing", None)
        if packing is not None:
            # Lane-packing provenance (parallel/packed.py): static per
            # round, stamped host-side like the codec accounting so
            # operators can tell packed from unpacked rows.
            row["pack_factor"] = int(packing.pack)
            row["packed_lanes"] = int(self.config.num_clients
                                      // packing.pack)
        if self._plan is not None:
            # Execution-autotuner provenance (perf/autotune.py): static
            # per trial, stamped host-side so every row names the plan
            # it ran under and how that plan was selected.  The full
            # candidate/timing breakdown rides the sweep summary
            # (plan_summary); rows carry the scalar slice.
            prov = self._plan_provenance or {}
            row["plan_id"] = self._plan.plan_id
            row["autotune_cache_hit"] = bool(prov.get("cache_hit"))
            row["autotune_timed"] = bool(prov.get("timed"))
            row["autotune_candidates"] = len(prov.get("candidates") or [])
        if "hbm_passes" in metrics:
            # Row-geometry pass-fusion accounting (streamed path): planned
            # full-matrix traversals per finish, fused plan vs the
            # per-statistic baseline (parallel/streamed_geometry.py).
            row["hbm_passes"] = int(metrics["hbm_passes"])
            row["hbm_passes_unfused"] = int(metrics["hbm_passes_unfused"])
        if "ici_bytes" in metrics:
            # Pod-scale ICI accounting (parallel/hier.py): per-round wire
            # bytes counted at trace time on the PassRecorder, plus the
            # pre-aggregated matrix height and the engaged device layout
            # — host-side stamps, the hbm_passes pattern.
            row["ici_bytes"] = int(metrics["ici_bytes"])
            row["preagg_kept"] = int(metrics["preagg_kept"])
            ms = getattr(self.config, "mesh_shape", None) or \
                (int(self.config.num_devices or 1), 1)
            row["mesh_shape"] = f"{int(ms[0])}x{int(ms[1])}"
        if "gossip_ici_bytes" in metrics:
            # Decentralized gossip accounting (blades_tpu/topology): the
            # neighborhood-exchange wire bytes counted at trace time, the
            # consensus diameter over round-input replicas, and the graph
            # provenance (static per run, stamped host-side so every row
            # names the topology it gossiped over).
            row["gossip_ici_bytes"] = int(metrics["gossip_ici_bytes"])
            row["num_partitioned_nodes"] = int(
                metrics["num_partitioned_nodes"])
            row["consensus_dist"] = float(metrics["consensus_dist"])
            prov = self._topology.provenance()
            row["topology"] = str(prov["topology"])
            row["graph_seed"] = int(prov["graph_seed"])
            row["spectral_gap"] = float(prov["spectral_gap"])
        if "elided_lanes" in metrics:
            # Malicious-lane training elision engaged (streamed/d-sharded
            # paths): surfaces the optimistic num_unhealthy basis — an
            # elided lane never trains, so it can never trip the health
            # counters (see parallel/dsharded.py caveats).
            row["elided_lanes"] = int(metrics["elided_lanes"])
        if self.config.fault_config:  # chaos layer (blades_tpu/faults)
            # Participation is per round; the dispatch summary reports the
            # LAST round (consistent with the scalar metrics above) plus
            # the static fault seed so a chaos run's stream is replayable.
            # Async cycles carry no participation mask (dropped arrivals
            # never enter the buffer; the drop counter rides the
            # arrival stamps instead), so only the seed lands here.
            if "num_participating" in metrics:
                for k in ("num_participating", "num_straggled",
                          "num_dropped"):
                    row[k] = int(metrics[k])
            row["fault_seed"] = int(self.fed_round.faults.seed)
        if "staleness_mean" in metrics:
            # Sync straggler path's staleness summary (core/round.py) —
            # the same schema fields the async stamps above use, so
            # sync-vs-async staleness reads from one place.
            row["staleness_mean"] = float(metrics["staleness_mean"])
            row["staleness_max"] = int(metrics["staleness_max"])
        if self.config.health_check or self.config.forensics:
            u = np.asarray(raw["num_unhealthy"])
            row["num_unhealthy"] = int(u.sum() if idx is None
                                       else (u[idx] if u.ndim else u))
        if self.config.health_check:  # failure-detection metrics (health.py)
            ok = np.asarray(raw["round_ok"])
            row["round_ok"] = bool(ok.all() if idx is None
                                   else (ok[idx] if ok.ndim else ok))
        if self.config.forensics:  # defense forensics (obs subsystem)
            for k in ("byz_precision", "byz_recall", "byz_fpr"):
                row[k] = metrics[k]
            row["num_flagged"] = int(metrics["num_flagged"])
            if cohort_ids is None:
                cohort_ids = np.arange(len(lanes["benign_mask"]),
                                       dtype=np.int64)
            # Cohort-shaped bundle: lane i diagnoses registered client
            # clients[i] (the identity arange on dense rounds, so
            # pre-cohort consumers read unchanged).
            row["lane_forensics"] = {
                "benign_mask": [bool(b > 0.5) for b in lanes["benign_mask"]],
                "healthy": [bool(h > 0.5) for h in lanes["healthy"]],
                "scores": [float(s) for s in lanes["scores"]],
                "clients": [int(c) for c in cohort_ids],
                "update_norms": [float(x)
                                 for x in lanes["update_norms"]],
            }
        if self._ledger is not None:
            # Client-lifetime ledger (obs/ledger.py): fold the round's
            # cohort into the longitudinal records — host-side over the
            # already-fetched lanes — then stamp the schema-registered
            # fleet fields into the row.  Without forensics only
            # participation/recency accrue (no diagnosis to fold).
            if self.config.forensics:
                flagged = np.asarray(lanes["benign_mask"]) <= 0.5
                scores = np.asarray(lanes["scores"], np.float64)
                norms = np.asarray(lanes["update_norms"], np.float64)
            else:
                flagged = scores = norms = None
            if cohort_ids is None:
                cohort_ids = np.arange(self.config.num_clients,
                                       dtype=np.int64)
            self._ledger.observe(
                cohort_ids, round=int(row["training_iteration"]),
                tick=row.get("tick"), flagged=flagged, scores=scores,
                staleness=cohort_staleness, norms=norms)
            row.update(self._ledger.round_fields())
        if self._controller is not None:
            # Closed-loop control (blades_tpu/control): runs LAST so the
            # watchdog and policy see the fully-stamped row (ledger
            # fleet fields included).
            self._control_round(row, lanes, cohort_ids)

    def _control_round(self, row: Dict, lanes: Dict, cohort_ids) -> None:
        """One control step over the finalized row: observe the driver's
        watchdog, stamp ``watchdog_events``, let the controller journal
        its policy decisions, apply them to the engine, and stamp the
        action fields the flight recorder replays bit-for-bit."""
        events = [e.as_dict() for e in self._watchdog.observe(row)]
        row["watchdog_events"] = events
        participants: tuple = ()
        flagged_ids: tuple = ()
        if cohort_ids is not None and "benign_mask" in lanes:
            ids = np.asarray(cohort_ids, np.int64)
            bad = np.asarray(lanes["benign_mask"]) <= 0.5
            participants = tuple(int(c) for c in ids)
            flagged_ids = tuple(int(c) for c in ids[bad])
        actions = self._controller.step(
            round_idx=int(row["training_iteration"]),
            tick=int(row.get("tick", row["training_iteration"])),
            events=events,
            suspects=row.get("ledger_top_suspects") or (),
            participants=participants, flagged=flagged_ids)
        for act in actions:
            self._apply_control_action(act)
        row["control_actions"] = [a.as_dict() for a in actions]
        row["control_actions_total"] = int(self._controller.actions_total)
        row["quarantine_size"] = len(self._controller.quarantine)

    def _apply_control_action(self, act) -> None:
        """Actuate one journaled decision.  A rejected engine move is a
        LOUD warning, never a crash — the journal records the intent
        either way, and view/engine divergence must be visible."""
        eng = self._async
        try:
            if act.actuator == "agg_every" and eng is not None:
                eng.set_agg_every(int(act.new))
            elif act.actuator == "buffer_capacity" and eng is not None:
                eng.set_buffer_capacity(int(act.new))
            elif act.actuator == "weight_cutoff" and eng is not None:
                eng.set_weight_cutoff(int(act.new))
            elif act.actuator == "window" and eng is not None:
                # Out-of-core participation window: the event-cohort
                # size under a state store IS the engine's agg_every —
                # a window shrink re-geometries the cycle (and the
                # store gathers) without touching the store itself.
                eng.set_agg_every(int(act.new))
            elif act.actuator in ("quarantine", "probe", "readmit",
                                  "requarantine"):
                if eng is not None:
                    eng.set_quarantine(
                        self._controller.quarantined_clients())
            elif act.actuator == "replan":
                self._replan_runtime()
        except ValueError as exc:
            warnings.warn(
                f"control action {act.actuator} (seq {act.seq}) was "
                f"journaled but the engine rejected it: {exc}",
                RuntimeWarning, stacklevel=2)

    def _replan_runtime(self) -> None:
        """Re-run the execution autotuner against current geometry and
        rebuild the round pipeline when the winner changed (sync
        dense path only — async × autotune is a forbidden config pair,
        and the windowed store must not be rebuilt mid-run)."""
        cfg = self.config
        if (not getattr(cfg, "autotune_mode", None) or self._async is not None
                or self._state_pf is not None or self.mesh is not None):
            return
        from blades_tpu.perf import autotune as at

        mode = cfg.autotune_mode
        space = self._plan_space(
            allow_reassociating=(mode == "reassociating"))
        measure = (at.timed_measure_fn(cfg) if at.timing_available()
                   else None)
        plan, prov = at.select_plan(space, measure_fn=measure)
        prov["mode"] = "replan"
        self._plan_provenance = prov
        if self._plan is not None and plan.as_dict() == self._plan.as_dict():
            return  # the standing plan won again — nothing to rebuild
        self._plan = plan
        self._apply_plan(plan)
        if self._use_streamed():
            # A replan is only offered within the dense plan space (the
            # controller gate above); a streamed resolution here would
            # mean the space drifted — refuse rather than rebuild wrong.
            warnings.warn("replan resolved a streamed plan mid-run; "
                          "keeping the standing pipeline",
                          RuntimeWarning, stacklevel=2)
            return
        self._setup_dense_pipeline()

    def train_rows(self, per_round: bool = False) -> List[Dict]:
        """One training dispatch, returned as result ROWS.

        ``per_round=False`` (or a single-round dispatch): exactly
        ``[self.train()]``.  ``per_round=True`` with
        ``rounds_per_dispatch > 1`` expands the dispatch's stacked
        metrics into one row per FL round — the sweep's scan-window
        path: per-round granularity on disk, ONE program dispatch and
        ONE batched ``device_get`` per window.  Rows before the window's
        final round carry the previous evaluation (the same
        repeat-last-eval convention as sequential rows); the final row
        carries whatever :meth:`_train_raw` attached (fresh eval when
        the cadence fired)."""
        if not per_round or self._chunk == 1:
            return [self.train()]
        prev_eval = dict(self._last_eval)
        start = self._iteration
        tail = self._train_raw(fetch=True)
        raw = tail.pop(DEVICE_METRICS_KEY)
        shared = {k: tail[k] for k in ("timers", "compile_cache_hits",
                                       "compile_cache_misses") if k in tail}
        eval_keys = {k: tail[k] for k in ("test_loss", "test_acc",
                                          "test_acc_top3") if k in tail}
        rows = []
        for r in range(self._chunk):
            row = {"training_iteration": start + r + 1, **shared}
            self._fill_round_metrics(row, raw, idx=r)
            row.update(eval_keys if r == self._chunk - 1 else prev_eval)
            rows.append(row)
        return rows

    def evaluate(self) -> Dict:
        """Weighted per-client evaluation (ref: fedavg.py:247-279)."""
        with self.timers.time("evaluate"):
            if self._eval_chunk_fn is not None:
                # Streaming eval (blades_tpu/data/stream): walk the
                # host test stack in bounded device-sized chunks — the
                # full stack is never device-put.  Differs from the
                # monolithic reduction only in summation order.
                from blades_tpu.data.stream import streaming_evaluate

                ev, n_chunks = streaming_evaluate(
                    self._eval_chunk_fn, self.state.server.params,
                    self._test_arrays,
                    chunk_clients=int(getattr(
                        self.config, "eval_chunk_clients", 256) or 256))
                self._eval_chunks = int(n_chunks)
                self._last_eval = {
                    "test_loss": float(ev["test_loss"]),
                    "test_acc": float(ev["test_acc"]),
                    "test_acc_top3": float(ev["test_acc_top3"]),
                    "eval_chunks": int(n_chunks),
                }
            else:
                ev = self._evaluate(self.state, *self._test_arrays)
                self._last_eval = {
                    "test_loss": float(ev["test_loss"]),
                    "test_acc": float(ev["test_acc"]),
                    "test_acc_top3": float(ev["test_acc_top3"]),
                }
        return dict(self._last_eval)

    # -- compiled-cost analysis (obs subsystem) ------------------------------

    _COST_KEYS = ("flops", "bytes accessed", "transcendentals")

    def cost_analysis(self) -> Optional[Dict]:
        """FLOPs / bytes of ONE compiled training dispatch, from XLA's own
        compiler estimate (``lower().compile().cost_analysis()``) — the
        hardware-speed denominator every BENCH MFU number needs.  Memoized
        (lowering re-traces; on backends without a shared AOT executable
        cache that is one extra compile per trial).  ``None`` when the
        executable or backend will not report costs — never raises.
        """
        if hasattr(self, "_cost_analysis"):
            return self._cost_analysis
        cost = None
        try:
            key = jax.random.PRNGKey(0)
            if self._prefetcher is not None:
                # The prebatched round program takes staged batches, not
                # the resident shards — lower it with matching arguments.
                bx, by = self._sample(key)
                args = (self.state, bx, by, self.malicious, key)
            else:
                args = (self.state, *self._train_arrays, self.malicious, key)
            lowered = self._step.lower(*args)
            ca = lowered.compile().cost_analysis()
            if isinstance(ca, (list, tuple)):  # older jax: one per device
                ca = ca[0] if ca else None
            if ca:
                cost = {
                    k.replace(" ", "_"): float(ca[k])
                    for k in self._COST_KEYS
                    if isinstance(ca.get(k), (int, float))
                } or None
        except Exception:
            cost = None
        self._cost_analysis = cost
        return cost

    # -- checkpointing (full state; fixes ref gap SURVEY.md §5) --------------

    def save_checkpoint(self, checkpoint_dir: str) -> str:
        path = Path(checkpoint_dir)
        path.mkdir(parents=True, exist_ok=True)
        state_for_pickle = self.state
        if self._state_store is not None:
            # Out-of-core per-client state: drain pending write-backs so
            # the store is authoritative, then checkpoint it as
            # STREAMING per-shard files (ClientStateStore.save — atomic
            # per shard, bounded memory at any population size) instead
            # of pickling the stacks.  The pickled RoundState carries
            # the replicated server only; the disposable cohort copy is
            # reconstructed from the store on resume.
            if self._state_pf is not None:
                self._state_pf.flush()
            state_for_pickle = _dc_replace(
                self.state, client_opt=None, residual=None, cohort=None)
        payload = {
            "iteration": self._iteration,
            "rounds_since_eval": self._rounds_since_eval,
            "key": jax.device_get(self._key),
            "state": jax.device_get(state_for_pickle),
            # Participation-window store provenance (blades_tpu/state):
            # present iff the per-client rows live in the sharded
            # `client_state/` checkpoint next to this pickle.
            "state_store": ({
                "backend": self._state_store.backend,
                "window": (int(self.config.state_window)
                           if self._state_pf is not None else None),
                "n_registered": self.config.num_clients,
            } if self._state_store is not None else None),
            # Out-of-core data provenance (blades_tpu/data): training
            # data is immutable and rebuildable from the dataset, so
            # the shard manifest is REFERENCED, never copied — the
            # checkpoint records which backend/directory served the
            # run and its population; resume re-opens (or rebuilds)
            # the cache from source.
            "data_store": ({
                "backend": self._data_store.backend,
                "dir": getattr(self._data_store, "directory", None),
                "n_clients": int(self._data_store.n_clients),
            } if self._data_store is not None else None),
            # Which client sits in each stacked row (the d-sharded
            # elision layout permutes clients at setup): lets a resume
            # under a DIFFERENT execution mode realign per-client state
            # instead of silently pairing client i's optimizer with
            # client j's data.
            "client_order": (None if self._client_order is None
                             else list(map(int, self._client_order))),
            # Lane-packing provenance.  RoundState stays in the canonical
            # UNPACKED layout on every path (pack/unpack wrap only the
            # local round), so unlike client_order there is nothing to
            # remap on resume — any pack_factor restores any other; the
            # value is recorded so a checkpoint's execution mode is
            # auditable.
            "pack_factor": (int(self.fed_round.packing.pack)
                            if getattr(self.fed_round, "packing", None)
                            is not None else 1),
            # Resolved execution plan (perf/autotune.py), recorded so a
            # kill-and-resume replays the IDENTICAL plan instead of
            # silently re-tuning mid-trajectory: the sweep runner pins
            # it back via config.tuned_plan before rebuilding (see
            # tune/sweep.py _pin_checkpoint_plan); load_checkpoint
            # warns on a mismatch for direct-API resumes.
            "plan": (self._plan.as_dict() if self._plan is not None
                     else None),
            # Buffered-async host state (blades_tpu/arrivals): the
            # virtual tick, version vector, pending arrival buffer and
            # drop counters — with the params-history ring already in
            # `state`, everything kill-and-resume needs to replay the
            # buffered trajectory bit-identically.
            "arrivals": (self._async.host_state()
                         if self._async is not None else None),
            # Closed-loop control state (blades_tpu/control): watchdog
            # rolling windows + controller journal/cooldowns/quarantine
            # — with these a kill-and-resume continues the EXACT action
            # journal a straight-through run would produce (the engine's
            # live actuator values ride the arrivals payload above).
            "control": ({"watchdog": self._watchdog.state(),
                         "controller": self._controller.state()}
                        if self._controller is not None else None),
            "config_dict": {k: v for k, v in self.config.items()
                            if not callable(v)},
        }
        file = path / "algorithm_state.pkl"
        with open(file, "wb") as f:
            pickle.dump(payload, f)
        if self._state_store is not None:
            self._state_store.save(path / "client_state")
        if self._ledger is not None:
            # Streaming shard checkpoint (ClientLedger.save: atomic per
            # shard, manifest-last) — the same contract as client_state/.
            self._ledger.save(path / "ledger")
        return str(file)

    def load_checkpoint(self, checkpoint_path: str) -> None:
        p = Path(checkpoint_path)
        if p.is_dir():
            p = p / "algorithm_state.pkl"
        with open(p, "rb") as f:
            payload = pickle.load(f)
        self._iteration = payload["iteration"]
        self._rounds_since_eval = payload.get("rounds_since_eval", 0)
        saved_plan = payload.get("plan")
        cur_plan = self._plan.as_dict() if self._plan is not None else None
        if saved_plan is not None and saved_plan != cur_plan:
            # Plan drift on resume: this instance resolved a different
            # execution plan than the one the checkpoint was written
            # under (a re-tune picked a new winner, or the plan cache
            # moved).  Default-tier plans are bit-identical so the
            # trajectory is safe either way, but reassociating-tier
            # drift silently changes numerics mid-run — surface it and
            # point at the pin.  The sweep runner never hits this: it
            # pins config.tuned_plan from the checkpoint before build.
            warnings.warn(
                f"checkpoint was written under execution plan "
                f"{saved_plan} but this instance resolved {cur_plan}; "
                "pin the saved plan via "
                "FedavgConfig.resources(tuned_plan=...) to replay it "
                "identically", RuntimeWarning, stacklevel=2)
        self._key = jnp.asarray(payload["key"])
        state = jax.tree.map(jnp.asarray, payload["state"])
        # Realign per-client state when the saved client layout differs
        # from this instance's (e.g. a dense-run checkpoint resumed on
        # the d-sharded elision layout, or vice versa).  Saved row j
        # holds client saved_order[j]; this instance's row i must hold
        # client cur_order[i].
        import numpy as np

        n = self.config.num_clients
        saved = payload.get("client_order") or list(range(n))
        cur = (list(range(n)) if self._client_order is None
               else list(map(int, self._client_order)))
        if saved != cur:
            inv_saved = np.argsort(np.asarray(saved))
            remap = jnp.asarray(inv_saved[np.asarray(cur)])
            state = type(state)(
                server=state.server,
                client_opt=jax.tree.map(lambda a: a[remap],
                                        state.client_opt),
                # Stale-update buffer rows are per-client too (chaos
                # layer); remap along its client axis (axis 1).
                stale=(None if getattr(state, "stale", None) is None
                       else state.stale[:, remap]),
                # Error-feedback residual rows are per-client as well
                # (comm subsystem); client axis is axis 0.
                residual=(None if getattr(state, "residual", None) is None
                          else state.residual[remap]),
                # The params-history ring has no client axis — versions
                # are global — so it rides the remap unchanged.
                arrivals=getattr(state, "arrivals", None),
            )
        import dataclasses as _dc

        saved_store = payload.get("state_store")
        if self._state_store is not None:
            ckpt_dir = p.parent
            if saved_store:
                # Streaming shard restore: validates per-shard sizes +
                # CRCs, deletes orphaned .tmp files, fails fast on a
                # torn/corrupt shard (StateStoreError).
                self._state_store.load(ckpt_dir / "client_state")
            elif getattr(state, "client_opt", None) is not None:
                # Monolithic (pre-window / resident-stack) checkpoint
                # resumed under a windowed store: scatter the stacks in.
                rows = {"client_opt": state.client_opt}
                if "residual" in (self._row_template or {}):
                    res = getattr(state, "residual", None)
                    if res is None:
                        # No EF residual in the checkpoint: the store
                        # keeps its cold zeros (the codec cold-start
                        # discipline).
                        rows = {"client_opt": state.client_opt,
                                "residual": np.zeros(
                                    (self.config.num_clients,)
                                    + tuple(self._row_template[
                                        "residual"].shape),
                                    np.float32)}
                    else:
                        rows["residual"] = res
                self._state_store.scatter(
                    np.arange(self.config.num_clients), rows)
                warnings.warn(
                    "resumed a monolithic checkpoint under a windowed "
                    "state store: per-client rows were scattered into "
                    "the store, but the saved aggregator state was "
                    "sized for the full population — stateful "
                    "aggregators may not restore cleanly",
                    RuntimeWarning, stacklevel=2)
            state = _dc.replace(state, client_opt=None, residual=None,
                                cohort=None)
            self._window_prev = None
            if self._state_pf is not None:
                self._state_pf.invalidate()
        elif saved_store:
            # Windowed-store checkpoint resumed on the resident path:
            # materialise the stacks from the shard files (same
            # size/CRC validation as the windowed restore).
            from blades_tpu.state import (client_state_template,
                                          read_checkpoint_rows)

            template = client_state_template(self.fed_round,
                                             state.server.params)
            rows = read_checkpoint_rows(p.parent / "client_state",
                                        template, self.config.num_clients)
            state = _dc.replace(
                state,
                client_opt=jax.tree.map(jnp.asarray, rows["client_opt"]),
                residual=(jnp.asarray(rows["residual"])
                          if "residual" in rows
                          else getattr(state, "residual", None)),
                cohort=None)
            warnings.warn(
                "resumed a windowed-store checkpoint on the resident "
                "path: per-client stacks were rebuilt from the shard "
                "files, but the saved aggregator state was sized for "
                "the window — stateful aggregators may not restore "
                "cleanly", RuntimeWarning, stacklevel=2)

        saved_data = payload.get("data_store")
        if saved_data:
            cur_backend = (self._data_store.backend
                           if self._data_store is not None else "resident")
            if saved_data.get("backend") != cur_backend:
                # Data backends are bit-identical by contract, so this
                # is provenance drift, not a numeric fork — but a
                # resume that silently changed where training shards
                # live should be operator-visible.
                warnings.warn(
                    "checkpoint was written under data_store="
                    f"{saved_data.get('backend')!r}; resuming under "
                    f"{cur_backend!r} (values are unaffected — data "
                    "backends are bit-identical by contract)",
                    RuntimeWarning, stacklevel=2)

        faults = self.fed_round.faults
        if (self._state_store is None and faults is not None
                and faults.needs_stale_buffer
                and getattr(state, "stale", None) is None):
            # Checkpoint from a run without a straggler process resumed
            # under one: start the ring buffer cold (zeros), exactly like
            # a fresh init.
            from blades_tpu.utils.tree import ravel_fn

            _, _, d = ravel_fn(state.server.params)
            state = _dc.replace(state, stale=faults.init_stale_buffer(n, d))
        codec = self.fed_round.codec
        if (self._state_store is None and codec is not None
                and codec.needs_residual
                and getattr(state, "residual", None) is None):
            # Checkpoint from a run without error feedback resumed under
            # a top-k+EF codec: start the residual cold (zeros), exactly
            # like a fresh init.
            from blades_tpu.utils.tree import ravel_fn

            _, _, d = ravel_fn(state.server.params)
            state = _dc.replace(state, residual=codec.init_residual(n, d))
        if self._async is not None:
            arr = payload.get("arrivals")
            if arr:
                self._async.restore_host_state(arr)
            else:
                # Checkpoint from a synchronous run (or from before the
                # arrivals subsystem) resumed under execution='async':
                # the arrival clock starts cold with the version counter
                # synced to the restored round — a fresh traffic
                # trajectory, NOT a bit-identical continuation.
                warnings.warn(
                    "checkpoint carries no arrivals payload; restarting "
                    "the arrival process cold at version "
                    f"{self._iteration} (the traffic trajectory will "
                    "differ from the original run)", RuntimeWarning,
                    stacklevel=2)
                self._async.cold_reset(self._iteration)
            if getattr(state, "arrivals", None) is None:
                # No params-history ring in the checkpoint: seed every
                # retained version with the restored params, exactly
                # like a fresh init.
                state = _dc.replace(
                    state,
                    arrivals=self._async.init_history(state.server.params))
        if self._controller is not None:
            ctl = payload.get("control")
            if ctl:
                self._watchdog.restore_state(ctl.get("watchdog") or {})
                self._controller.restore(ctl.get("controller") or {})
                if self._async is not None:
                    # The engine's live actuator values rode the
                    # arrivals payload; re-assert from the controller's
                    # view only where an older payload left defaults.
                    v = self._controller.values
                    # Under an out-of-core store the `window` view is
                    # the live cohort size (window moves actuate
                    # set_agg_every); prefer it over the untouched
                    # agg_every view so a resumed shrink is kept.
                    want_k = v.get("window") or v.get("agg_every")
                    if want_k and int(want_k) != self._async.agg_every:
                        self._async.set_agg_every(int(want_k))
                    if (v.get("weight_cutoff") is not None
                            and int(v["weight_cutoff"])
                            != self._async.weight_cutoff):
                        self._async.set_weight_cutoff(
                            int(v["weight_cutoff"]))
                    held = self._controller.quarantined_clients()
                    if held != self._async.quarantine:
                        self._async.set_quarantine(held)
            else:
                # Checkpoint from an uncontrolled run resumed under
                # control: the controller starts cold at the restored
                # round — the journal before it is unrecoverable.
                warnings.warn(
                    "checkpoint carries no control payload; the "
                    "controller starts cold at round "
                    f"{self._iteration} (the action journal before it "
                    "is not recoverable)", RuntimeWarning, stacklevel=2)
        if self.mesh is not None:
            if self.config.execution == "gossip":
                # The checkpoint carries the (n_pad, ...) per-node params
                # stack verbatim; re-lay it on the gossip mesh without
                # re-broadcasting (kill-and-resume bit-identity).
                from blades_tpu.topology import reshard_gossip_state

                state = reshard_gossip_state(self.mesh, state)
            else:
                from blades_tpu.parallel import shard_federation

                state, _ = shard_federation(self.mesh, state, ())
        if self._ledger is not None:
            ledger_dir = p.parent / "ledger"
            if (ledger_dir / "manifest.json").exists():
                # Bit-identical longitudinal restore (sizes + CRCs
                # validated per shard; LedgerError on a torn file).
                self._ledger.load(ledger_dir)
            else:
                # Checkpoint from a ledger-less run: the records start
                # cold at the restored round — participation counts
                # before it are unrecoverable, and the warning says so.
                warnings.warn(
                    "checkpoint carries no ledger/ shards; the client "
                    "ledger starts cold at round "
                    f"{self._iteration} (longitudinal records before "
                    "it are not recoverable)", RuntimeWarning,
                    stacklevel=2)
        self.state = state
        if self._prefetcher is not None:
            # The key chain rewound: any staged batches belong to the
            # pre-restore timeline and must not feed a restored round.
            self._prefetcher.invalidate()

    # -- misc ---------------------------------------------------------------

    def stop(self) -> None:
        if self._state_pf is not None:
            self._state_pf.close()
        if self._state_store is not None:
            self._state_store.close()
        if self._data_pf is not None:
            self._data_pf.close()  # closes the DataStore behind it too
        if self._ledger is not None:
            self._ledger.close()
