"""Algorithm registry (ref: blades/algorithms/registry.py:22-50)."""

from __future__ import annotations

from typing import Tuple, Type


def _fedavg():
    from blades_tpu.algorithms.config import FedavgConfig
    from blades_tpu.algorithms.fedavg import Fedavg

    return Fedavg, FedavgConfig


def _fedavg_dp():
    from blades_tpu.algorithms.fedavg import Fedavg
    from blades_tpu.algorithms.fedavg_dp import FedavgDPConfig

    return Fedavg, FedavgDPConfig


ALGORITHMS = {
    "FEDAVG": _fedavg,
    "FEDAVG_DP": _fedavg_dp,
}


def get_algorithm_class(name: str, return_config: bool = False):
    """(ref: registry.py:28-50)"""
    key = name.upper()
    if key not in ALGORITHMS:
        raise KeyError(f"unknown algorithm {name!r}; known: {sorted(ALGORITHMS)}")
    cls, cfg_cls = ALGORITHMS[key]()
    if return_config:
        return cls, cfg_cls()
    return cls
