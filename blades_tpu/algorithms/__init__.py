"""Algorithm layer: config system + FedAvg / FedAvg-DP drivers
(ref: fllib/algorithms/ + blades/algorithms/).

``FedavgConfig`` is the fluent builder (ref: fllib/algorithms/
algorithm_config.py) — ``.data().training().client().adversary()
.evaluation()`` then ``.build()`` — producing a ``Fedavg`` driver whose
``train()`` runs one round (the Tune-Trainable ``step`` contract,
ref: fllib/algorithms/algorithm.py:102-119) and whose checkpoints carry
FULL state (params + server opt + aggregator + per-client opt + RNG),
fixing the reference's config-only checkpoint gap (SURVEY.md §5).
"""

from blades_tpu.algorithms.config import FedavgConfig  # noqa: F401
from blades_tpu.algorithms.fedavg import Fedavg  # noqa: F401
from blades_tpu.algorithms.fedavg_dp import FedavgDPConfig  # noqa: F401
from blades_tpu.algorithms.registry import ALGORITHMS, get_algorithm_class  # noqa: F401
